"""Training forensics (ISSUE 5): step-time attribution timeline,
flight recorder dump triggers (crash / non-finite loss / serve SLO
breach / explicit), anomaly + straggler detection, the bench
regression gate, and the device-peak-FLOPs table under a TPU stub."""

import glob
import json
import os
import sys
import time

import numpy as np
import pytest

import parallax_tpu as parallax
from parallax_tpu import obs
from parallax_tpu.common import flops as flops_lib
from parallax_tpu.common.config import AnomalyConfig
from parallax_tpu.models import simple
from parallax_tpu.obs import aggregate
from parallax_tpu.obs.anomaly import AnomalyMonitor
from parallax_tpu.obs.flightrec import FlightRecorder
from parallax_tpu.obs.metrics import MetricsRegistry
from parallax_tpu.obs.timeline import StepTimeline

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _simple_session(**cfg_kw):
    sess, *_ = parallax.parallel_run(
        simple.build_model(learning_rate=0.1),
        parallax_config=parallax.Config(run_option="AR",
                                        search_partitions=False,
                                        **cfg_kw))
    return sess


def _batches(n, batch=64, seed=0):
    rng = np.random.default_rng(seed)
    return [simple.make_batch(rng, batch) for _ in range(n)]


# -- step-time attribution (obs/timeline.py) -------------------------------


class TestStepTimeline:
    def test_rows_components_and_residual(self):
        tl = StepTimeline(MetricsRegistry(), capacity=8)
        tl.record_step(0, ts=0.0, wall_s=0.010, data_wait_s=0.002,
                       convert_s=0.001, h2d_s=0.001, dispatch_s=0.004,
                       fetch_block_s=0.001)
        (row,) = tl.rows()
        assert row["wall_ms"] == pytest.approx(10.0)
        assert row["data_wait_ms"] == pytest.approx(2.0)
        # dispatch is net of its inner h2d + fetch-block shares
        assert row["dispatch_ms"] == pytest.approx(2.0)
        attributed = (row["data_wait_ms"] + row["convert_ms"]
                      + row["h2d_ms"] + row["dispatch_ms"]
                      + row["fetch_block_ms"])
        assert row["device_est_ms"] == pytest.approx(10.0 - attributed)
        assert row["mfu"] is None  # no flops attached

    def test_ring_eviction_and_fetch_block_attribution(self):
        tl = StepTimeline(MetricsRegistry(), capacity=4)
        for s in range(10):
            tl.record_step(s, ts=float(s), wall_s=0.01,
                           dispatch_s=0.01)
        rows = tl.rows()
        assert [r["step"] for r in rows] == [6, 7, 8, 9]
        assert tl.total_rows == 10
        # lazy fetch attributed back to its (still-ringed) step
        tl.add_fetch_block(8, 0.005)
        row8 = next(r for r in tl.rows() if r["step"] == 8)
        assert row8["fetch_block_ms"] == pytest.approx(5.0)
        # an evicted step's fetch-block is dropped, not crashed on
        tl.add_fetch_block(0, 0.005)

    def test_pre_dispatch_h2d_not_subtracted_from_dispatch(self):
        """The place-batch-then-step pattern: placement paid BEFORE the
        step call counts as H2D but must not be subtracted from a
        dispatch share that never contained it."""
        tl = StepTimeline(MetricsRegistry(), capacity=4)
        tl.record_step(0, ts=0.0, wall_s=0.020, dispatch_s=0.004,
                       h2d_pre_s=0.010)
        (row,) = tl.rows()
        assert row["h2d_ms"] == pytest.approx(10.0)
        assert row["dispatch_ms"] == pytest.approx(4.0)  # not clamped

    def test_mfu_and_goodput_account(self):
        tl = StepTimeline(MetricsRegistry(), capacity=8)
        for s in range(4):
            tl.record_step(s, ts=0.0, wall_s=0.010, data_wait_s=0.002,
                           dispatch_s=0.003)
        # 1e9 FLOPs per 10ms step against a 1e12 FLOP/s peak = 0.1 MFU
        tl.set_flops(1e9, 1e12)
        rows = tl.rows()
        assert rows[-1]["mfu"] == pytest.approx(0.1)
        g = tl.goodput()
        assert g["steps"] == 4
        assert g["mfu_mean"] == pytest.approx(0.1)
        assert g["phase_frac"]["data_wait_ms"] == pytest.approx(0.2)
        fracs = sum(v for v in g["phase_frac"].values())
        assert fracs == pytest.approx(1.0, abs=1e-6)
        json.dumps(g)  # JSON-ready

    def test_registry_gauges_and_disabled_noop(self):
        reg = MetricsRegistry()
        tl = StepTimeline(reg, capacity=8)
        tl.record_step(0, ts=0.0, wall_s=0.01, dispatch_s=0.004)
        snap = reg.snapshot()
        assert snap["timeline.wall_ms"]["p50"] == pytest.approx(10.0)
        assert snap["timeline.steps"] == 1
        obs.disable()
        try:
            tl.record_step(1, ts=0.0, wall_s=0.01)
            tl.add_fetch_block(0, 1.0)
        finally:
            obs.enable()
        assert tl.total_rows == 1
        assert tl.rows()[0]["fetch_block_ms"] == 0.0


# -- anomaly detection (obs/anomaly.py) ------------------------------------


def _cfg(**kw):
    base = dict(window=32, min_samples=8, spike_mads=6.0,
                spike_min_ratio=2.0, shift_window=4, shift_ratio=1.5,
                cooldown=16)
    base.update(kw)
    return AnomalyConfig(**base)


class TestAnomaly:
    def test_spike_fires_and_counts(self):
        reg = MetricsRegistry()
        am = AnomalyMonitor(reg, _cfg())
        for i in range(20):
            assert am.observe("step_time_ms", i,
                              10.0 + 0.1 * (i % 3)) is None
        ev = am.observe("step_time_ms", 20, 200.0)
        assert ev is not None and ev.kind == "spike"
        assert ev.step == 20 and ev.ratio > 10
        assert reg.counter("anomaly.step_time_ms.spikes").value == 1
        assert am.events()[0]["signal"] == "step_time_ms"

    def test_cooldown_suppresses_repeat_firing(self):
        am = AnomalyMonitor(MetricsRegistry(), _cfg(cooldown=16))
        for i in range(20):
            am.observe("s", i, 10.0)
        assert am.observe("s", 20, 300.0) is not None
        # within cooldown: an equal outlier stays silent
        assert am.observe("s", 21, 300.0) is None

    def test_shift_detects_sustained_regression_and_rebaselines(self):
        reg = MetricsRegistry()
        am = AnomalyMonitor(reg, _cfg(spike_min_ratio=10.0))
        for i in range(30):
            am.observe("s", i, 10.0 + 0.01 * (i % 5))
        # a sustained 1.8x level change (no single sample is a spike
        # at spike_min_ratio=10): the change-point detector must fire
        fired = None
        for i in range(30, 50):
            ev = am.observe("s", i, 18.0)
            if ev is not None:
                fired = ev
                break
        assert fired is not None and fired.kind == "shift"
        # fires as soon as the recent mean crosses shift_ratio x the
        # baseline (the mean still mixes a few old-level samples)
        assert fired.ratio >= 1.5
        assert fired.baseline == pytest.approx(10.0, rel=0.05)
        assert reg.counter("anomaly.s.shifts").value == 1
        # rebaselined: the new level is now normal — no refiring even
        # after cooldown expires
        for i in range(50, 120):
            assert am.observe("s", i, 18.0) is None

    def test_stable_signal_never_fires_and_disabled_noop(self):
        am = AnomalyMonitor(MetricsRegistry(), _cfg())
        for i in range(200):
            assert am.observe("s", i, 5.0 + 0.05 * (i % 7)) is None
        obs.disable()
        try:
            n = am.total_observed
            am.observe("s", 999, 1e9)
        finally:
            obs.enable()
        assert am.total_observed == n

    def test_on_event_callback(self):
        got = []
        am = AnomalyMonitor(MetricsRegistry(), _cfg(),
                            on_event=got.append)
        for i in range(20):
            am.observe("s", i, 1.0)
        am.observe("s", 20, 50.0)
        assert len(got) == 1 and got[0].kind == "spike"


# -- flight recorder (obs/flightrec.py) ------------------------------------


class TestFlightRecorder:
    def test_dump_sections_and_provider_isolation(self, tmp_path):
        def boom():
            raise RuntimeError("poisoned buffer")
        fr = FlightRecorder(
            flight_dir=str(tmp_path),
            providers={"good": lambda: {"x": 1}, "bad": boom})
        path = fr.dump("manual", detail={"k": "v"})
        doc = json.load(open(path))
        assert doc["reason"] == "manual"
        assert doc["detail"] == {"k": "v"}
        assert doc["good"] == {"x": 1}
        assert "RuntimeError" in doc["bad"]["_error"]
        assert doc["process_index"] == 0

    def test_trigger_requires_flight_dir_and_dedups(self, tmp_path):
        fr = FlightRecorder(flight_dir=None)
        assert fr.trigger("nonfinite_loss") is None  # not armed
        fr = FlightRecorder(flight_dir=str(tmp_path))
        p1 = fr.trigger("nonfinite_loss:a", {"step": 1})
        assert p1 is not None
        # same reason KEY: suppressed (one artifact per incident class)
        assert fr.trigger("nonfinite_loss:b", {"step": 2}) is None
        # a different incident class still dumps
        assert fr.trigger("serve_deadline_breach") is not None
        assert len(fr.dump_paths) == 2

    def test_max_dumps_cap(self, tmp_path):
        fr = FlightRecorder(flight_dir=str(tmp_path), max_dumps=2)
        assert fr.trigger("a") and fr.trigger("b")
        assert fr.trigger("c") is None
        assert len(fr.dump_paths) == 2

    def test_suppressed_dumps_counted_per_class(self, tmp_path):
        """ISSUE 12 satellite: a rate-limited trigger must leave a
        countable trace per incident class — a 9th incident of a
        class shows up in flightrec.suppressed.<class> instead of
        vanishing without record."""
        from parallax_tpu.obs.metrics import MetricsRegistry
        reg = MetricsRegistry()
        fr = FlightRecorder(flight_dir=str(tmp_path), registry=reg,
                            max_dumps=2)
        assert fr.trigger("nonfinite_loss:a") is not None
        for _ in range(3):  # same class: suppressed, counted
            assert fr.trigger("nonfinite_loss:b") is None
        assert fr.trigger("serve_deadline_breach") is not None
        assert fr.trigger("fleet_crash:r0") is None  # max_dumps cap
        snap = reg.snapshot()
        assert snap["flightrec.suppressed.nonfinite_loss"] == 3
        assert snap["flightrec.suppressed.fleet_crash"] == 1
        assert snap["flight.dumps_suppressed"] == 4  # aggregate kept
        assert snap["flight.dumps"] == 2

    def test_artifacts_carry_incident_ids(self, tmp_path):
        fr = FlightRecorder(flight_dir=str(tmp_path))
        p1 = fr.trigger("a")
        p2 = fr.trigger("b")
        id1 = json.load(open(p1))["incident_id"]
        id2 = json.load(open(p2))["incident_id"]
        assert id1 and id2 and id1 != id2
        assert fr.last_incident_id == id2


# -- straggler aggregation (obs/aggregate.py) ------------------------------


class TestAggregate:
    def test_find_stragglers(self):
        assert aggregate.find_stragglers([10, 10, 10, 10]) == []
        assert aggregate.find_stragglers([10, 31, 10, 10],
                                         factor=1.25) == [1]
        assert aggregate.find_stragglers([10]) == []  # single host
        assert aggregate.find_stragglers([10, 13, 40, 41],
                                         factor=1.3) == [2, 3]

    def test_build_report_names_the_laggard(self):
        rows = np.array([[10.0, 12.0, 50], [41.0, 52.0, 50],
                         [11.0, 13.0, 50]])
        rep = aggregate.build_report(rows, factor=1.25)
        assert rep["num_hosts"] == 3
        assert rep["stragglers"] == [1]
        assert rep["slowest"] == 1
        assert rep["hosts"][1]["straggler"] is True
        assert rep["hosts"][1]["vs_median"] == pytest.approx(
            41 / 11.0, abs=1e-3)
        line = aggregate.straggler_summary(rep)
        assert "process 1" in line
        assert aggregate.straggler_summary(
            aggregate.build_report(np.array([[10.0, 11.0, 5],
                                             [10.5, 11.0, 5]]))) is None
        json.dumps(rep)

    def test_single_process_collective(self):
        rep = aggregate.aggregate_host_step_times(
            {"mean_ms": 5.0, "p95_ms": 7.0, "steps": 12})
        assert rep["num_hosts"] == 1
        assert rep["stragglers"] == []
        assert rep["hosts"][0]["steps"] == 12


# -- session integration ---------------------------------------------------


class TestSessionForensics:
    def test_timeline_attribution_through_run_and_run_iter(self):
        sess = _simple_session()
        try:
            sess.run("loss", feed_dict=_batches(1)[0])
            (row,) = sess.timeline.rows()
            # the run() path converts + places on the dispatch thread
            assert row["convert_ms"] > 0
            assert row["h2d_ms"] > 0
            assert row["dispatch_ms"] > 0
            for r in sess.run_iter(_batches(6), "loss"):
                float(r)
            rows = sess.timeline.rows()
            assert len(rows) == 7
            # preplaced batches: H2D overlapped on the prefetch thread,
            # so the dispatch rows carry no critical-path H2D...
            assert all(r["h2d_ms"] == 0.0 for r in rows[1:])
            # ...and waiting on the prefetcher is attributed data-wait
            assert any(r["data_wait_ms"] > 0 for r in rows[1:])
            snap = sess.metrics_snapshot()
            assert snap["timeline.steps"] == 7
            assert snap["timeline.wall_ms"]["count"] == 7
        finally:
            sess.close()

    def test_explicit_dump_flight_without_flight_dir(self, tmp_path):
        sess = _simple_session()
        try:
            for b in _batches(3):
                sess.run("loss", feed_dict=b)
            path = sess.dump_flight(str(tmp_path / "post.json"))
            doc = json.load(open(path))
            assert doc["reason"] == "manual"
            assert len(doc["steps"]) == 3
            assert doc["goodput"]["steps"] == 3
            assert doc["config"]["run_option"] == "AR"
            assert doc["metrics"]["pipeline.steps"] == 3
            assert doc["progress"]["host_step"] == 3
        finally:
            sess.close()

    def test_crash_dump_on_step_exception(self, tmp_path):
        """Acceptance: a crash escaping a step leaves a post-mortem
        artifact (and the exception still propagates)."""
        sess = _simple_session(flight_dir=str(tmp_path))
        try:
            for b in _batches(2):
                sess.run("loss", feed_dict=b)
            bad = {"x": _batches(1)[0]["x"]}  # missing the 'y' feed
            with pytest.raises(Exception):
                sess.run("loss", feed_dict=bad)
            dumps = glob.glob(str(tmp_path / "flight_exception*.json"))
            assert len(dumps) == 1
            doc = json.load(open(dumps[0]))
            assert doc["reason"].startswith("exception:")
            assert doc["detail"]["step"] == 2
            assert len(doc["steps"]) == 2  # the history before death
        finally:
            sess.close()

    def test_nan_loss_triggers_flight_dump(self, tmp_path):
        """Acceptance: an injected NaN loss produces a flight artifact
        naming the step."""
        sess = _simple_session(monitor_health=True,
                               flight_dir=str(tmp_path))
        try:
            good = _batches(3)
            bad = _batches(1, seed=9)[0]
            bad["x"] = np.full_like(bad["x"], np.nan)
            for b in (good[0], good[1], bad, good[2]):
                sess.run("loss", feed_dict=b)
            sess.health.poll(block=True)
            dumps = glob.glob(str(tmp_path / "flight_nonfinite_loss*"))
            assert len(dumps) == 1
            doc = json.load(open(dumps[0]))
            assert doc["detail"]["step"] == 2
            assert doc["health"]["first_nonfinite_step"] == 2
            readings = doc["health"]["readings"]
            assert any(r["loss_finite"] is False for r in readings)
        finally:
            sess.close()

    def test_step_flops_after_warmup_feeds_timeline(self):
        sess = _simple_session()
        try:
            b = _batches(1)[0]
            sess.warmup(feed_dict=b, batch_sizes=[64])
            flops = sess.step_flops()  # cheap: AOT executable exists
            assert flops is not None and flops > 0
            # CPU: peak is None, so MFU must stay null — never faked
            sess.run("loss", feed_dict=b)
            assert sess.timeline.goodput()["flops_per_step"] == flops
            assert sess.timeline.goodput()["mfu_mean"] is None
        finally:
            sess.close()

    def test_place_batch_then_step_attributes_h2d(self):
        """Same-thread sess.place_batch -> placed step: the placement
        lands in the step's row as H2D without zeroing dispatch."""
        sess = _simple_session()
        try:
            placed = sess.place_batch(_batches(1)[0])
            (res,) = list(sess.run_iter(iter([placed]), "loss",
                                        placed=True))
            float(res)
            (row,) = sess.timeline.rows()
            assert row["h2d_ms"] > 0          # the pre-step placement
            assert row["dispatch_ms"] > 0     # not clamped to zero
        finally:
            sess.close()

    def test_step_flops_noncheap_retraces_when_no_executable(self):
        sess = _simple_session()
        try:
            sess.run("loss", feed_dict=_batches(1)[0])
            # no AOT executable: the cheap (monitoring) path refuses
            assert sess.step_flops() is None
            # the explicit path re-traces + lowers once and caches
            f = sess.step_flops(cheap_only=False)
            assert f is not None and f > 0
            assert sess.step_flops() == f  # now cached, cheap too
        finally:
            sess.close()

    def test_host_aggregation_lands_in_dump(self, tmp_path):
        sess = _simple_session()
        try:
            for b in _batches(4):
                sess.run("loss", feed_dict=b)
            rep = sess.aggregate_host_steps()
            assert rep["num_hosts"] == 1 and rep["stragglers"] == []
            doc = json.load(open(sess.dump_flight(
                str(tmp_path / "agg.json"))))
            assert doc["host_report"]["num_hosts"] == 1
        finally:
            sess.close()


# -- serve SLO breach trigger ----------------------------------------------


class TestServeSLOBreachDump:
    def test_deadline_breach_triggers_flight_dump(self, tmp_path):
        """Acceptance: a serve deadline breach produces a flight
        artifact (the queue sheds the expired request, the breach hook
        fires through the recorder)."""
        from parallax_tpu.serve import ServeSession
        from parallax_tpu.serve.batcher import DeadlineExceeded
        fr = FlightRecorder(flight_dir=str(tmp_path))
        serve = ServeSession(
            lambda params, batch: {"y": batch["x"]},
            {"w": np.zeros((1,), np.float32)},
            example_feed={"x": np.zeros((4,), np.float32)},
            config=parallax.Config(serve_config=parallax.ServeConfig(
                max_batch=2, max_wait_ms=30.0, max_queue=8)),
            flight=fr)
        try:
            req = serve.submit({"x": np.ones((4,), np.float32)},
                               deadline_ms=0.01)
            deadline = time.perf_counter() + 10.0
            while not req.done() and time.perf_counter() < deadline:
                time.sleep(0.01)
            assert req.done()
            with pytest.raises(DeadlineExceeded):
                req.result()
            # the breach hook fired a dump (queue or dispatch path)
            ok = time.perf_counter() + 5.0
            while not fr.dump_paths and time.perf_counter() < ok:
                time.sleep(0.01)
            dumps = glob.glob(
                str(tmp_path / "flight_serve_deadline_breach*"))
            assert len(dumps) == 1
            doc = json.load(open(dumps[0]))
            assert doc["detail"]["n"] >= 1
        finally:
            serve.close()


# -- regression gate (tools/check_regression.py) ---------------------------


def _bench_block(value=4000.0, version=2, sha="abc123", **kw):
    block = {"metric": "lm1b_words_per_sec_per_chip", "value": value,
             "unit": "words/sec/chip", "platform": "cpu", "n_chips": 8,
             "bench_version": version,
             "harness": {"bench_sha256": sha, "steps_measured": 30}}
    block.update(kw)
    return block


class TestRegressionGate:
    def _compare(self, cur, prev, **kw):
        from tools.check_regression import compare
        return compare(cur, prev, **kw)

    def test_unchanged_rerun_passes(self):
        r = self._compare(_bench_block(4000.0), _bench_block(4010.0))
        assert r["status"] == "ok"
        assert r["harness_verified"] is True

    def test_catches_injected_2x_slowdown(self):
        """Acceptance: a 2x step-time slowdown (headline halves)
        between harness-compatible rounds FAILS the gate."""
        r = self._compare(_bench_block(2000.0), _bench_block(4000.0))
        assert r["status"] == "regression"
        assert r["ratio"] == pytest.approx(0.5)

    def test_regression_note_explains(self):
        r = self._compare(
            _bench_block(2000.0, regression_note="vocab doubled"),
            _bench_block(4000.0))
        assert r["status"] == "explained"

    def test_version_bump_needs_ab_block(self):
        cur = _bench_block(2000.0, version=3)
        prev = _bench_block(4000.0, version=2)
        r = self._compare(cur, prev)
        assert r["status"] == "not_comparable"
        assert "ab_vs_prev_harness" in r["why"]
        # A/B shows the move is methodology: same build under prev
        # params holds the old number -> explained
        cur["ab_vs_prev_harness"] = {"value_under_prev_params": 3900.0}
        r = self._compare(cur, prev)
        assert r["status"] == "explained"
        assert r["ab_ratio"] == pytest.approx(0.975)

    def test_version_bump_cannot_amnesty_a_build_regression(self):
        """The gate judges the A/B's apples-to-apples ratio: a build
        that regressed 2x cannot hide behind a bench_version bump."""
        cur = _bench_block(2000.0, version=3)
        prev = _bench_block(4000.0, version=2)
        cur["ab_vs_prev_harness"] = {"value_under_prev_params": 2000.0}
        r = self._compare(cur, prev)
        assert r["status"] == "regression"
        assert r["ab_ratio"] == pytest.approx(0.5)
        cur["regression_note"] = "accepted: bf16 accumulate change"
        assert self._compare(cur, prev)["status"] == "explained"

    def test_harness_edit_within_version_not_comparable(self):
        r = self._compare(_bench_block(2000.0, sha="NEW"),
                          _bench_block(4000.0, sha="OLD"))
        assert r["status"] == "not_comparable"

    def test_platform_or_chips_mismatch_not_comparable(self):
        r = self._compare(_bench_block(8000.0, platform="tpu"),
                          _bench_block(4000.0))
        assert r["status"] == "not_comparable"

    def test_failed_round_never_gates(self):
        r = self._compare(_bench_block(0.0, error="worker exited"),
                          _bench_block(4000.0))
        assert r["status"] == "no_data"

    def test_suspicious_rise_flagged_but_passes(self):
        r = self._compare(_bench_block(9000.0), _bench_block(4000.0))
        assert r["status"] == "suspicious_rise"

    def test_main_on_wrapped_artifacts(self, tmp_path):
        """End to end through the CLI against driver-format files:
        unchanged rerun exits 0, injected 2x slowdown exits 1."""
        from tools.check_regression import main
        prev = tmp_path / "BENCH_r05.json"
        cur = tmp_path / "BENCH_r06.json"
        prev.write_text(json.dumps(
            {"n": 5, "rc": 0, "parsed": _bench_block(4000.0)}))
        cur.write_text(json.dumps(
            {"n": 6, "rc": 0, "parsed": _bench_block(3900.0)}))
        assert main([str(cur), str(prev)]) == 0
        cur.write_text(json.dumps(
            {"n": 6, "rc": 0, "parsed": _bench_block(2000.0)}))
        assert main([str(cur), str(prev)]) == 1

    def test_discovery_orders_by_round_number(self, tmp_path):
        from tools.check_regression import discover_rounds
        for n in (2, 10, 9):
            (tmp_path / f"BENCH_r{n:02d}.json").write_text("{}")
        cur, prev = discover_rounds(str(tmp_path))
        assert cur.endswith("BENCH_r10.json")
        assert prev.endswith("BENCH_r09.json")

    def test_wrapper_truncation_recovers_harness_from_tail(
            self, tmp_path):
        """ISSUE 7 satellite: the r05 driver wrapper truncated the
        parsed block (no ``harness``), which made the r5->r6 gate
        report not_comparable for want of an A/B replay. load_block
        must backfill missing top-level keys from the raw result line
        in the wrapper's stdout tail — parsed values win on
        conflict — so a wrapped artifact round-trips whole."""
        from tools.bench_artifacts import load_block
        full = _bench_block(4000.0)
        full["harness"] = {"bench_sha256": "abc123", "batch_size": 128,
                          "steps_measured": 20}
        full["serve"] = {"qps": 55.0}
        truncated = {k: v for k, v in full.items()
                     if k not in ("harness", "serve")}
        truncated["value"] = 4001.0  # parsed wins on conflict
        p = tmp_path / "BENCH_r09.json"
        p.write_text(json.dumps({
            "n": 9, "rc": 0,
            "tail": ("PARALLAX INFO: noise\n" + json.dumps(full)
                     + "\n"),
            "parsed": truncated}))
        blk = load_block(str(p))
        assert blk["harness"] == full["harness"]
        assert blk["serve"] == full["serve"]
        assert blk["value"] == 4001.0
        # an untruncated wrapper round-trips to itself
        p2 = tmp_path / "BENCH_r10.json"
        p2.write_text(json.dumps({
            "n": 10, "rc": 0, "tail": json.dumps(full),
            "parsed": full}))
        assert load_block(str(p2)) == full
        # a tail whose result line measured a DIFFERENT metric never
        # backfills (recovering someone else's harness would be worse
        # than recovering nothing)
        other = dict(full, metric="other_metric")
        p3 = tmp_path / "BENCH_r11.json"
        p3.write_text(json.dumps({
            "n": 11, "rc": 0, "tail": json.dumps(other),
            "parsed": truncated}))
        assert "harness" not in load_block(str(p3))


# -- device peak FLOPs under a TPU stub (VERDICT r5 item 5) ---------------


class TestDevicePeakFlops:
    def test_platform_gate_and_table(self):
        f = flops_lib.device_peak_flops
        assert f("cpu", "cpu") is None          # fallback: no number
        assert f("gpu", "NVIDIA H100") is None
        assert f("tpu", "TPU v4") == 275e12
        assert f("tpu", "TPU v5e") == 197e12
        assert f("tpu", "TPU v5p") == 459e12
        assert f("tpu", "TPU v6 lite") == 918e12
        # opaque kind + env gen hint resolves (the tunnel case)
        assert f("tpu", "", "v5e") == 197e12
        # unknown TPU: None, never a wrong number
        assert f("tpu", "TPU v99") is None

    def test_mfu_nonnull_the_moment_platform_is_tpu(self):
        """bench.py's exact computation under a v5e stub: a non-null
        MFU lands without any TPU-side special-casing."""
        from parallax_tpu.models import lm1b
        cfg = lm1b.tiny_config(num_partitions=8)
        fpw = flops_lib.lm1b_matmul_flops_per_word(cfg)
        peak = flops_lib.device_peak_flops("tpu", "TPU v5e", None)
        value = flops_lib.mfu(fpw, 1e6, peak)
        assert value is not None and 0 < value < 1
        assert flops_lib.mfu(fpw, 1e6, None) is None  # CPU: null


# -- bench harness A/B decision (VERDICT r5 item 6) ------------------------


class TestBenchHarnessAB:
    def test_needs_ab_only_on_version_bump_with_harness(self):
        import bench
        prev = {"bench_version": bench.BENCH_VERSION - 1,
                "harness": {"batch_size": 128}}
        assert bench._needs_harness_ab(prev)
        assert not bench._needs_harness_ab(
            {"bench_version": bench.BENCH_VERSION,
             "harness": {"batch_size": 128}})
        assert not bench._needs_harness_ab(
            {"bench_version": bench.BENCH_VERSION - 1})  # no harness
        assert not bench._needs_harness_ab(None)

    def test_load_prev_round_unwraps_driver_format(self, tmp_path):
        import bench
        (tmp_path / "BENCH_r04.json").write_text(json.dumps(
            {"parsed": {"value": 1.0, "bench_version": 1}}))
        (tmp_path / "BENCH_r05.json").write_text(json.dumps(
            {"parsed": {"value": 2.0, "bench_version": 2}}))
        prev = bench._load_prev_round(str(tmp_path))
        assert prev == {"value": 2.0, "bench_version": 2}
        assert bench._load_prev_round(str(tmp_path / "none")) is None


# -- bench_resnet tracking number (VERDICT r5 item 5) ----------------------


class TestResnetVsPrev:
    def _result(self, **kw):
        base = {"value": 0.1, "platform": "cpu", "n_chips": 8,
                "model": "resnet50_v1.5", "image_size": 224,
                "classes": 1000, "per_chip_batch": 2}
        base.update(kw)
        return base

    def test_comparable_rounds_track(self):
        from tools.bench_resnet import vs_prev
        ratio, why = vs_prev(self._result(value=0.05),
                             self._result(value=0.1))
        assert ratio == pytest.approx(0.5)  # the 2x regression shows
        assert why == "comparable"

    def test_shape_or_platform_change_never_fakes_a_ratio(self):
        from tools.bench_resnet import vs_prev
        ratio, why = vs_prev(self._result(),
                             self._result(image_size=64))
        assert ratio is None and "image_size" in why
        ratio, why = vs_prev(self._result(),
                             self._result(platform="tpu"))
        assert ratio is None
        assert vs_prev(self._result(), None)[0] is None
        assert vs_prev(self._result(), self._result(value=0))[0] is None
