"""Fault-tolerant serving fleet (ISSUE 7): replicated engines,
health-aware router, chaos harness, live weight hot-swap.

Covers the error taxonomy (retryable declared on the exception, not
pattern-matched), request done-callbacks, the router state machine
(error-rate/heartbeat/latency probes, circuit breaker with exponential
backoff and probation) driven deterministically with explicit clocks,
the fault injector, one-shot fleet integration (failover on crash and
NaN, saturation spill, hot-swap that actually changes outputs with
zero recompiles), the autoscaler over fake replicas, the anomaly
rebaseline path for deliberate scale events, the fleet secondary
regression gates, paged-KV decode failover token identity in-process,
and the tier-1 chaos guard (tools/check_fleet_faults.py via the
established subprocess driver).
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

import parallax_tpu as parallax
from parallax_tpu import ServeConfig
from parallax_tpu.core import mesh as mesh_lib
from parallax_tpu.serve import (DeadlineExceeded, FaultInjector,
                                FleetConfig, HealthPolicy,
                                PagePoolExhausted, ReplicaCrash,
                                ReplicaUnavailable, Request, Router,
                                ServeClosed, ServeError, ServeFleet,
                                ServeOverloaded, ServeSession)
from parallax_tpu.serve.router import (DEGRADED, DRAINING, EJECTED,
                                       HEALTHY)
from test_compile import _run_driver_json


# -- error taxonomy (declared, not pattern-matched) -------------------------


class TestErrorTaxonomy:
    def test_retryable_is_declared_on_the_class(self):
        assert ServeOverloaded.retryable is True
        assert ReplicaUnavailable.retryable is True
        assert PagePoolExhausted.retryable is True
        assert ReplicaCrash.retryable is True
        assert DeadlineExceeded.retryable is False
        assert ServeClosed.retryable is False
        assert ServeError.retryable is False

    def test_fatal_marks_replica_death_only(self):
        assert ReplicaCrash.fatal is True
        for exc in (ServeOverloaded, DeadlineExceeded, ServeClosed,
                    ReplicaUnavailable, PagePoolExhausted):
            assert getattr(exc, "fatal", False) is False, exc


# -- request done-callbacks -------------------------------------------------


class TestDoneCallbacks:
    def test_callback_fires_on_completion_and_failure(self):
        seen = []
        r = Request({"x": 1})
        r.add_done_callback(lambda req: seen.append(("done", req.id)))
        r._complete(42)
        assert seen == [("done", r.id)]
        r2 = Request({"x": 2})
        r2.add_done_callback(lambda req: seen.append("failed"))
        r2._fail(ServeError("boom"))
        assert seen[-1] == "failed"

    def test_callback_on_already_done_request_fires_immediately(self):
        r = Request({"x": 1})
        r._complete("y")
        seen = []
        r.add_done_callback(lambda req: seen.append(req._result))
        assert seen == ["y"]

    def test_broken_callback_does_not_break_delivery(self):
        r = Request({"x": 1})
        r.add_done_callback(lambda req: 1 / 0)
        r._complete("ok")
        assert r.result(timeout=1.0) == "ok"


# -- the router state machine (deterministic clocks) ------------------------


class _FakeSession:
    """Duck-typed replica for router/autoscaler units: no jax, no
    threads — load/heartbeat/alive set directly by the test."""

    def __init__(self, load=0.0):
        self._load = float(load)
        self.alive = True
        self.heartbeat = 0.0
        self.closed = False

    def load(self):
        return self._load

    def idle(self):
        return self._load == 0.0

    def close(self, drain=True):
        self.closed = True


def _policy(**kw):
    base = dict(window=4, min_outcomes=2, degrade_error_rate=0.25,
                eject_error_rate=0.5, recovery_idle_s=100.0,
                heartbeat_timeout_s=1.0, backoff_initial_s=1.0,
                backoff_max_s=8.0, probation_successes=2)
    base.update(kw)
    return HealthPolicy(**base)


class TestRouter:
    def test_places_least_loaded_healthy(self):
        r = Router(_policy())
        a = r.add("a", _FakeSession(load=5.0))
        b = r.add("b", _FakeSession(load=1.0))
        h = r.place()
        assert h is b
        r.done_placing(h)
        # a pending placement counts as load (drain-race accounting)
        b.session._load = 0.0
        a.session._load = 0.0
        h1 = r.place()
        h2 = r.place()
        assert {h1.rid, h2.rid} == {"a", "b"}
        r.done_placing(h1)
        r.done_placing(h2)

    def test_draining_and_excluded_take_no_placement(self):
        r = Router(_policy())
        r.add("a", _FakeSession())
        r.add("b", _FakeSession())
        r.set_draining("a", True)
        for _ in range(4):
            h = r.place()
            assert h.rid == "b"
            r.done_placing(h)
        with pytest.raises(ReplicaUnavailable):
            r.place(exclude=("b",))
        r.set_draining("a", False)
        assert r.get("a").state == HEALTHY

    def test_drain_restore_keeps_probation_debt(self):
        """A hot-swap rotation of a DEGRADED probationer must not
        launder it to HEALTHY: it comes back DEGRADED, still owing
        its probation successes, and serves them out normally."""
        r = Router(_policy())
        h = r.add("a", _FakeSession())
        r.record_error(h, ServeError("x"), now=0.0)
        r.record_error(h, ServeError("x"), now=0.0)
        h.session.heartbeat = 1.1
        r.tick(now=1.1)
        assert h.state == DEGRADED and h.probation_left == 2
        r.set_draining("a", True, now=1.2)     # rotation begins
        assert h.state == DRAINING
        r.set_draining("a", False, now=1.3)    # rotation complete
        assert h.state == DEGRADED             # NOT healthy
        assert h.probation_left == 2           # debt intact
        r.record_success(h, now=1.4)
        r.record_success(h, now=1.5)
        assert h.state == HEALTHY and h.ejections == 0

    def test_degraded_only_when_healthy_unavailable(self):
        r = Router(_policy(degraded_penalty=1e6))
        a = r.add("a", _FakeSession(load=100.0))
        b = r.add("b", _FakeSession(load=0.0))
        r.record_error(b, ServeError("x"), now=0.0)
        r.record_error(b, ServeError("x"), now=0.0)
        assert b.state == EJECTED  # rate 1.0 >= eject
        h = r.place()
        assert h is a
        r.done_placing(h)

    def test_error_rate_degrades_then_ejects_with_backoff(self):
        r = Router(_policy(window=8, min_outcomes=4))
        h = r.add("a", _FakeSession())
        for _ in range(6):
            r.record_success(h, now=0.0)
        r.record_error(h, ServeError("x"), now=0.0)
        r.record_error(h, ServeError("x"), now=0.0)
        assert h.state == DEGRADED          # 2/8 = 0.25 >= degrade
        for _ in range(3):
            r.record_error(h, ServeError("x"), now=0.0)
        assert h.state == EJECTED           # window rate >= 0.5
        assert h.reopen_at == pytest.approx(1.0)  # initial backoff

    def test_circuit_reopens_into_probation_then_healthy(self):
        r = Router(_policy())
        h = r.add("a", _FakeSession())
        r.record_error(h, ServeError("x"), now=0.0)
        r.record_error(h, ServeError("x"), now=0.0)
        assert h.state == EJECTED and h.ejections == 1
        h.session.heartbeat = 0.5
        r.tick(now=0.5)
        assert h.state == EJECTED           # circuit still open
        h.session.heartbeat = 1.1
        r.tick(now=1.1)
        assert h.state == DEGRADED and h.probation_left == 2
        r.record_success(h, now=1.2)
        assert h.state == DEGRADED
        r.record_success(h, now=1.3)
        assert h.state == HEALTHY
        assert h.ejections == 0             # clean bill resets backoff

    def test_error_during_probation_reejects_with_doubled_backoff(self):
        r = Router(_policy())
        h = r.add("a", _FakeSession())
        r.record_error(h, ServeError("x"), now=0.0)
        r.record_error(h, ServeError("x"), now=0.0)
        h.session.heartbeat = 1.1
        r.tick(now=1.1)
        assert h.state == DEGRADED
        r.record_error(h, ServeError("x"), now=1.2)
        assert h.state == EJECTED and h.ejections == 2
        assert h.reopen_at == pytest.approx(1.2 + 2.0)  # doubled
        # backoff is capped
        for k in range(3, 9):
            h.session.heartbeat = h.reopen_at
            r.tick(now=h.reopen_at)
            r.record_error(h, ServeError("x"), now=h.reopen_at)
        assert h.reopen_at - h.last_error_at <= 8.0 + 1e-9

    def test_stale_heartbeat_degrades_then_ejects(self):
        r = Router(_policy(heartbeat_timeout_s=1.0))
        h = r.add("a", _FakeSession())
        h.session.heartbeat = 0.0
        r.tick(now=1.5)
        assert h.state == DEGRADED
        r.tick(now=3.5)                      # stale > 3x timeout
        assert h.state == EJECTED
        # stall clears -> circuit reopens -> probation -> healthy
        reopen = h.reopen_at
        h.session.heartbeat = reopen
        r.tick(now=reopen)
        assert h.state == DEGRADED
        r.record_success(h, now=reopen)
        r.record_success(h, now=reopen)
        assert h.state == HEALTHY

    def test_heartbeat_recovery_without_probation(self):
        """A degrade (not eject) recovers on tick once the condition
        clears — no probation owed."""
        r = Router(_policy(heartbeat_timeout_s=1.0))
        h = r.add("a", _FakeSession())
        h.session.heartbeat = 0.0
        r.tick(now=1.5)
        assert h.state == DEGRADED
        h.session.heartbeat = 2.0
        r.tick(now=2.1)
        assert h.state == HEALTHY

    def test_latency_straggler_degrades(self):
        r = Router(_policy(latency_degrade_ratio=3.0))
        a = r.add("a", _FakeSession())
        b = r.add("b", _FakeSession())
        for _ in range(4):
            r.record_success(a, latency_ms=10.0, now=0.0)
            r.record_success(b, latency_ms=100.0, now=0.0)
        a.session.heartbeat = b.session.heartbeat = 0.1
        r.tick(now=0.1)
        assert a.state == HEALTHY
        assert b.state == DEGRADED
        assert "latency" in b.state_reason

    def test_probation_gets_probe_placements_and_recovers(self):
        """The circuit-breaker half-open trickle: with a healthy
        sibling always preferred, a probationer would starve without
        the every-probe_every-th probe placement — and could never
        serve the successes probation demands."""
        r = Router(_policy(probe_every=4))
        a = r.add("a", _FakeSession(load=0.0))
        b = r.add("b", _FakeSession(load=0.0))
        r.record_error(b, ServeError("x"), now=0.0)
        r.record_error(b, ServeError("x"), now=0.0)
        assert b.state == EJECTED
        b.session.heartbeat = 1.1
        r.tick(now=1.1)
        assert b.state == DEGRADED and b.probation_left == 2
        placed = []
        for _ in range(12):
            h = r.place()
            placed.append(h.rid)
            r.record_success(h, now=1.2)
            r.done_placing(h)
        assert placed.count("b") >= 2, placed
        assert b.state == HEALTHY

    def test_dead_session_is_ejected_permanently(self):
        r = Router(_policy())
        h = r.add("a", _FakeSession())
        h.session.alive = False
        r.tick(now=0.0)
        assert h.state == EJECTED and h.dead
        assert h.reopen_at is None
        r.tick(now=1e9)                      # never re-admits
        assert h.state == EJECTED

    def test_state_changes_report_through_callback(self):
        events = []
        r = Router(_policy(), on_state_change=lambda h, o, n, why:
                   events.append((h.rid, o, n)))
        h = r.add("a", _FakeSession())
        r.record_error(h, ServeError("x"), now=0.0)
        r.record_error(h, ServeError("x"), now=0.0)
        assert events == [("a", HEALTHY, EJECTED)]


# -- the fault injector -----------------------------------------------------


class TestFaultInjector:
    def test_crash_fires_once(self):
        inj = FaultInjector()
        inj.arm(0, "crash")
        with pytest.raises(ReplicaCrash):
            inj.on_dispatch(0)
        assert inj.on_dispatch(0) is None    # dead is dead: one shot
        assert inj.fired("crash") == 1

    def test_faults_are_per_replica(self):
        inj = FaultInjector()
        inj.arm(1, "nan")
        assert inj.on_dispatch(0) is None
        assert inj.on_dispatch(1) == "nan"
        assert inj.on_dispatch(1) is None    # times=1 default

    def test_stall_sleeps(self):
        inj = FaultInjector()
        inj.arm(0, "stall", seconds=0.05)
        t0 = time.perf_counter()
        inj.on_dispatch(0)
        assert time.perf_counter() - t0 >= 0.04

    def test_saturate_sheds_until_cleared(self):
        inj = FaultInjector()
        inj.arm(0, "saturate", times=None)
        with pytest.raises(ServeOverloaded):
            inj.on_admission(0)
        with pytest.raises(ServeOverloaded):
            inj.on_admission(0)
        inj.clear(0, "saturate")
        inj.on_admission(0)                  # no raise

    def test_arm_validates(self):
        inj = FaultInjector()
        with pytest.raises(ValueError, match="kind"):
            inj.arm(0, "gremlins")
        with pytest.raises(ValueError, match="seconds"):
            inj.arm(0, "stall")


# -- one-shot fleet integration ---------------------------------------------


_DIM = 8


def _mlp_fleet(replicas=2, faults=None, anomaly=None, flight=None,
               w_scale=1.0, fleet_kw=None, serve_kw=None):
    """A tiny-MLP one-shot fleet on ONE shared mesh (the in-process
    multi-mesh caution from PR 3 applies; the chaos guard's subprocess
    exercises per-replica submeshes)."""
    params = {"w": np.eye(_DIM, dtype=np.float32) * w_scale}

    def infer_fn(p, b):
        return {"y": (b["x"] @ p["w"]).mean(axis=(1, 2))}

    cfg = parallax.Config(serve_config=ServeConfig(
        max_batch=2, max_wait_ms=1.0, **(serve_kw or {})))
    mesh = mesh_lib.build_mesh()

    def make_replica(rid, **kw):
        return ServeSession(
            infer_fn, params,
            example_feed={"x": np.zeros((4, _DIM), np.float32)},
            config=cfg, mesh=mesh, **kw)

    fc = FleetConfig(num_replicas=replicas, **(fleet_kw or {}))
    return ServeFleet(make_replica, config=fc, faults=faults,
                      anomaly=anomaly, flight=flight), params


def _feed(v):
    return {"x": np.full((4, _DIM), float(v), np.float32)}


class TestFleetOneShot:
    def test_serves_correctly_across_replicas(self):
        fleet, _ = _mlp_fleet()
        try:
            reqs = [fleet.submit(_feed(i)) for i in range(10)]
            for i, r in enumerate(reqs):
                np.testing.assert_allclose(
                    r.result(timeout=30.0)["y"], i, rtol=1e-5)
            s = fleet.stats()
            assert s["fleet.completed"] == 10
            assert s["fleet.replicas_healthy"] == 2
            assert fleet.recompiles() == 0
        finally:
            fleet.close()

    def test_crash_fails_over_without_losing_requests(self):
        inj = FaultInjector()
        fleet, _ = _mlp_fleet(faults=inj)
        try:
            inj.arm(0, "crash")
            reqs = [fleet.submit(_feed(i)) for i in range(8)]
            for i, r in enumerate(reqs):
                np.testing.assert_allclose(
                    r.result(timeout=30.0)["y"], i, rtol=1e-5)
            s = fleet.stats()
            assert s["replicas"]["0"]["state"] == EJECTED
            assert s["replicas"]["0"]["dead"] is True
            assert s["fleet.ejections"] >= 1
            # at least the batch in flight when the crash fired (plus
            # anything queued behind it) failed over
            assert s["fleet.failovers"] >= 1
            assert s["fleet.failed"] == 0
        finally:
            fleet.close()

    def test_failover_trail_recorded_on_the_request(self):
        inj = FaultInjector()
        fleet, _ = _mlp_fleet(faults=inj)
        try:
            inj.arm(0, "crash")
            reqs = [fleet.submit(_feed(i)) for i in range(8)]
            for i, r in enumerate(reqs):
                np.testing.assert_allclose(
                    r.result(timeout=30.0)["y"], i, rtol=1e-5)
            # the crash fired on replica 0's first dispatched batch,
            # so the requests it held show the two-hop trail
            trails = [r.replicas for r in reqs]
            assert any(t == [0, 1] for t in trails), trails
        finally:
            fleet.close()

    def test_whole_fleet_death_fails_fast_and_retryably(self):
        inj = FaultInjector()
        fleet, _ = _mlp_fleet(faults=inj)
        try:
            inj.arm(0, "crash")
            inj.arm(1, "crash")
            reqs = [fleet.submit(_feed(i)) for i in range(4)]
            for r in reqs:
                # never hangs, never delivers garbage: each request
                # fails promptly with the RETRYABLE error once no
                # replica remains (a client tier may resubmit later)
                with pytest.raises(ReplicaUnavailable):
                    r.result(timeout=30.0)
        finally:
            fleet.close()

    def test_nan_output_is_detected_and_retried(self):
        """check_outputs (fleet default): a NaN batch fails RETRYABLY
        instead of reaching a client, and the retry serves real
        numbers from a healthy replica."""
        inj = FaultInjector()
        fleet, _ = _mlp_fleet(faults=inj)
        try:
            inj.arm(0, "nan", times=1)
            inj.arm(1, "nan", times=1)
            reqs = [fleet.submit(_feed(i)) for i in range(8)]
            for i, r in enumerate(reqs):
                out = r.result(timeout=30.0)
                assert np.isfinite(out["y"]).all()
                np.testing.assert_allclose(out["y"], i, rtol=1e-5)
            assert fleet.stats()["fleet.retries"] >= 1
        finally:
            fleet.close()

    def test_saturation_spills_then_sheds_fleet_wide(self):
        inj = FaultInjector()
        fleet, _ = _mlp_fleet(faults=inj)
        try:
            inj.arm(0, "saturate", times=None)
            # one replica saturated: traffic spills to the other
            reqs = [fleet.submit(_feed(i)) for i in range(4)]
            for i, r in enumerate(reqs):
                np.testing.assert_allclose(
                    r.result(timeout=30.0)["y"], i, rtol=1e-5)
            assert all(r.replicas == [1] for r in reqs)
            # every replica saturated: the fleet sheds synchronously
            inj.arm(1, "saturate", times=None)
            with pytest.raises(ServeOverloaded):
                fleet.submit(_feed(0))
            assert fleet.stats()["fleet.shed"] == 1
        finally:
            fleet.close()

    def test_hot_swap_takes_effect_with_zero_recompiles(self):
        fleet, params = _mlp_fleet()
        try:
            r = fleet.submit(_feed(3))
            np.testing.assert_allclose(r.result(timeout=30.0)["y"],
                                       3.0, rtol=1e-5)
            outcome = fleet.push_weights(
                {"w": np.eye(_DIM, dtype=np.float32) * 2.0})
            assert set(outcome.values()) == {"swapped"}
            r = fleet.submit(_feed(3))
            np.testing.assert_allclose(r.result(timeout=30.0)["y"],
                                       6.0, rtol=1e-5)
            s = fleet.stats()
            assert s["fleet.hotswaps"] == 2
            assert s["fleet.drain_seconds"]["count"] == 2
            assert s["fleet.replicas_healthy"] == 2
            assert fleet.recompiles() == 0
        finally:
            fleet.close()

    def test_scale_up_after_push_serves_pushed_weights(self):
        """Stale weights must not rejoin — including via scale-up: a
        replica added AFTER push_weights comes up on the pushed
        checkpoint, not on whatever the factory closure captured."""
        fleet, _ = _mlp_fleet(fleet_kw={"max_replicas": 3})
        try:
            fleet.push_weights(
                {"w": np.eye(_DIM, dtype=np.float32) * 2.0})
            rid = fleet.scale_up()
            assert rid is not None
            # route to the newcomer specifically
            h = fleet._router.get(rid)
            sub = h.session.submit(_feed(3))
            np.testing.assert_allclose(sub.result(timeout=30.0)["y"],
                                       6.0, rtol=1e-5)
            assert fleet.recompiles() == 0
        finally:
            fleet.close()

    def test_one_bad_batch_does_not_eject_a_replica(self):
        """Error accounting is per REQUEST, symmetric with success
        accounting — a single transient bad batch on a warm replica
        must not blow through the ejection threshold."""
        inj = FaultInjector()
        fleet, _ = _mlp_fleet(faults=inj)
        try:
            # warm both replicas' outcome windows with successes
            for i in range(12):
                fleet.submit(_feed(i)).result(timeout=30.0)
            inj.arm(0, "nan", times=1)
            inj.arm(1, "nan", times=1)
            reqs = [fleet.submit(_feed(i)) for i in range(4)]
            for i, r in enumerate(reqs):
                np.testing.assert_allclose(
                    r.result(timeout=30.0)["y"], i, rtol=1e-5)
            s = fleet.stats()
            # a DEGRADE is fine (each replica did take a bad batch);
            # an EJECTION — halving capacity over one transient — is
            # the double-counting bug this test pins down
            assert s["fleet.ejections"] == 0, s["replicas"]
            assert all(v["state"] in (HEALTHY, DEGRADED)
                       for v in s["replicas"].values()), s["replicas"]
        finally:
            fleet.close()

    def test_swap_refuses_architecture_change(self):
        fleet, _ = _mlp_fleet()
        try:
            with pytest.raises(RuntimeError, match="hot-swap failed"):
                fleet.push_weights(
                    {"w": np.zeros((_DIM, _DIM + 1), np.float32)})
            # the refusing replicas are ejected (stale weights must
            # not rejoin silently) and the failure is counted
            s = fleet.stats()
            assert s["fleet.hotswap_failures"] == 2
            assert all(v["state"] == EJECTED
                       for v in s["replicas"].values())
        finally:
            fleet.close()

    def test_deadline_respected_across_failover(self):
        """A retry never extends the budget: with every replica dead,
        the request fails promptly (retryably) instead of spinning."""
        inj = FaultInjector()
        fleet, _ = _mlp_fleet(faults=inj)
        try:
            inj.arm(0, "crash")
            inj.arm(1, "crash")
            admitted = 0
            for i in range(4):
                try:
                    r = fleet.submit(_feed(i), deadline_ms=5000.0)
                except ReplicaUnavailable:
                    # the whole fleet died before this submit — a
                    # synchronous refusal at admission is also correct
                    continue
                admitted += 1
                with pytest.raises((ReplicaUnavailable,
                                    DeadlineExceeded)):
                    r.result(timeout=30.0)
            assert admitted >= 1  # the first submit always lands
        finally:
            fleet.close()

    def test_submit_after_close_raises(self):
        fleet, _ = _mlp_fleet()
        fleet.close()
        with pytest.raises(ServeClosed):
            fleet.submit(_feed(0))


# -- autoscaler (fake replicas, deterministic) ------------------------------


class TestAutoscaler:
    def _fleet(self, **fc_kw):
        sessions = []

        def make_replica(rid, **kw):
            s = _FakeSession(load=0.0)
            s.heartbeat = time.perf_counter()
            sessions.append(s)
            return s

        fc = FleetConfig(num_replicas=1, min_replicas=1,
                         max_replicas=3, autoscale=True,
                         autoscale_high_load=4.0,
                         autoscale_low_load=0.5,
                         autoscale_sustain_ticks=2,
                         tick_interval_s=3600.0,  # test drives ticks
                         **fc_kw)
        return ServeFleet(make_replica, config=fc), sessions

    @staticmethod
    def _settle(fleet, n, timeout=5.0):
        """Scale actions run OFF the maintenance thread (a drain or a
        cold compile must not freeze the health probes) — wait for the
        spawned action to land."""
        end = time.perf_counter() + timeout
        while time.perf_counter() < end:
            if fleet.num_replicas == n and not fleet._autoscale_busy:
                return
            time.sleep(0.005)
        raise AssertionError(
            f"fleet did not settle at {n} replicas "
            f"(at {fleet.num_replicas})")

    def test_scales_up_on_sustained_pressure_only(self):
        fleet, sessions = self._fleet()
        try:
            sessions[0]._load = 10.0
            fleet._autoscale_tick()          # 1 tick: not sustained
            assert fleet.num_replicas == 1
            fleet._autoscale_tick()          # sustained -> scale up
            self._settle(fleet, 2)
            assert fleet.stats()["fleet.scale_ups"] == 1
            # a blip does not scale: counter resets between
            sessions[0]._load = 1.0
            sessions[1]._load = 1.0
            fleet._autoscale_tick()
            sessions[0]._load = 10.0
            sessions[1]._load = 10.0
            fleet._autoscale_tick()
            self._settle(fleet, 2)

        finally:
            fleet.close()

    def test_scales_down_via_graceful_drain_never_below_min(self):
        fleet, sessions = self._fleet()
        try:
            sessions[0]._load = 10.0
            fleet._autoscale_tick()
            fleet._autoscale_tick()
            self._settle(fleet, 2)
            sessions[0]._load = 0.0
            fleet._autoscale_tick()
            fleet._autoscale_tick()
            self._settle(fleet, 1)
            assert any(s.closed for s in sessions)  # drained close
            fleet._autoscale_tick()
            fleet._autoscale_tick()
            self._settle(fleet, 1)           # min_replicas floor
        finally:
            fleet.close()

    def test_scale_up_bounded_by_max_replicas(self):
        fleet, sessions = self._fleet()
        try:
            assert fleet.scale_up() is not None
            assert fleet.scale_up() is not None
            assert fleet.scale_up() is None  # at max_replicas=3
            assert fleet.num_replicas == 3
        finally:
            fleet.close()


# -- deliberate changes must not read as anomalies --------------------------


class TestAnomalyRebaseline:
    def _monitor(self):
        from parallax_tpu.common.config import AnomalyConfig
        from parallax_tpu.obs.anomaly import AnomalyMonitor
        return AnomalyMonitor(config=AnomalyConfig(
            window=32, min_samples=8, shift_window=4,
            shift_ratio=1.5, cooldown=16))

    def test_level_change_fires_shift_without_notice(self):
        # 10 -> 16: a sustained +60% level move — below the 2x spike
        # ratio, above the 1.5x shift ratio (the change-point case)
        mon = self._monitor()
        events = [e for i in range(20)
                  if (e := mon.observe("step_time_ms", i, 10.0))]
        assert not events
        fired = [mon.observe("step_time_ms", 20 + i, 16.0)
                 for i in range(8)]
        assert any(e is not None and e.kind == "shift" for e in fired)

    def test_notified_scale_event_does_not_fire(self):
        mon = self._monitor()
        for i in range(20):
            assert mon.observe("step_time_ms", i, 10.0) is None
        # the fleet announces the deliberate change (scale-up,
        # ejection failover, hot-swap) -> rebaseline, no change-point
        mon.notify_deliberate_change("fleet scale-up")
        for i in range(30):
            assert mon.observe("step_time_ms", 20 + i, 16.0) is None
        snap = mon.registry.snapshot()
        assert snap["anomaly.deliberate_changes"] == 1
        assert "anomaly.step_time_ms.shifts" not in snap

    def test_fleet_scale_event_reaches_the_monitor(self):
        mon = self._monitor()
        sessions = []

        def make_replica(rid, **kw):
            s = _FakeSession()
            s.heartbeat = time.perf_counter()
            sessions.append(s)
            return s

        fleet = ServeFleet(make_replica,
                           config=FleetConfig(num_replicas=1,
                                              max_replicas=2,
                                              tick_interval_s=3600.0),
                           anomaly=mon)
        try:
            fleet.scale_up()
            assert mon.registry.snapshot()[
                "anomaly.deliberate_changes"] >= 1
        finally:
            fleet.close()


# -- fleet secondary regression gates ---------------------------------------


class TestFleetSecondaryGates:
    @staticmethod
    def _doc(recovery=60.0, blackout=40.0):
        return {"bench_version": 3, "value": 1000.0,
                "serve": {"fleet": {
                    "failover_recovery_ms": recovery,
                    "hotswap_blackout_ms": blackout}}}

    def _run(self, cur, prev):
        from tools.check_regression import compare_secondary
        return {r["gate"]: r for r in compare_secondary(cur, prev)}

    def test_recovery_regression_fails(self):
        res = self._run(self._doc(recovery=200.0),
                        self._doc(recovery=60.0))
        assert res["serve.fleet.failover_recovery_ms"]["status"] \
            == "regression"
        assert res["serve.fleet.hotswap_blackout_ms"]["status"] == "ok"

    def test_missing_fleet_block_skips(self):
        cur, prev = self._doc(), self._doc()
        del prev["serve"]["fleet"]
        res = self._run(cur, prev)
        assert res["serve.fleet.failover_recovery_ms"]["status"] \
            == "skipped"
        assert res["serve.fleet.hotswap_blackout_ms"]["status"] \
            == "skipped"


# -- decode failover token identity (paged KV, in-process) ------------------


def test_decode_failover_token_identity_paged():
    """ISSUE 7 satellite: a request retried onto a second replica
    after an injected crash emits the SAME greedy tokens as an
    unfaulted standalone decode — under a paged-KV program, where the
    dead replica's pages are simply abandoned with it and the retry
    allocates fresh ones on the survivor. Shared mesh (in-process
    multi-mesh caution); the subprocess chaos guard covers the
    per-replica-submesh shape.

    ISSUE 12 satellite, same rig: cross-thread ``trace.record_span``
    under failover — each logical request surfaces EXACTLY ONE
    ``serve.request`` span (the dead hop never retires, so only the
    delivering replica emits), carrying the final replica id and the
    hop count."""
    from parallax_tpu.models import nmt
    from parallax_tpu.obs import trace
    from tools import loadgen

    inj = FaultInjector()
    fleet, make_feed, params, cfg = loadgen.demo_decode_fleet(
        replicas=2, slots=2, T=8, Ts=6, model_dim=16, vocab=64,
        page_size=4, faults=inj, submesh=False)
    n = 8
    col = trace.TraceCollector(capacity=4096)
    prev = trace.set_collector(col)
    try:
        reqs = [fleet.submit(make_feed(i)) for i in range(n)]
        while sum(1 for r in reqs if r.done()) < 1:
            time.sleep(0.005)
        victim = max((h for h in fleet._router.handles()
                      if h.session.alive),
                     key=lambda h: h.session.load())
        inj.arm(victim.rid, "crash")
        outs = [r.result(timeout=120.0) for r in reqs]
        retried = [r for r in reqs if len(r.replicas) > 1]
        assert retried, "the crash caused no failover"
        assert fleet.recompiles() == 0
    finally:
        fleet.close()
        trace.set_collector(prev)
    for i, (r, out) in enumerate(zip(reqs, outs)):
        src = make_feed(i)["src"]
        ref = np.asarray(nmt.greedy_decode(
            params, cfg, src[None], max_len=8))[0].tolist()
        if nmt.EOS_ID in ref:
            ref = ref[:ref.index(nmt.EOS_ID) + 1]
        assert list(out) == ref, (i, r.replicas, list(out), ref)
    # the trace contract: one span per logical request, final replica
    # id + hop count in-args (keyed by the fleet request id the shared
    # lifecycle record carries across hops)
    spans = {}
    for ev in col.events():
        if ev.name == "serve.request":
            spans.setdefault(ev.args["rid"], []).append(ev)
    for r in reqs:
        assert len(spans.get(r.id, [])) == 1, \
            (r.id, r.replicas, spans.get(r.id))
        args = spans[r.id][0].args
        assert args["replica"] == r.replicas[-1], (args, r.replicas)
        assert args["hops"] == len(r.replicas), (args, r.replicas)
    survivor_hops = {len(r.replicas) for r in retried}
    assert survivor_hops == {2}


def test_incident_dump_correlates_fleet_state(tmp_path):
    """ISSUE 12: a replica crash produces ONE correlated artifact —
    shared incident id, the crashed replica named, every affected
    request id with its failover hop trail, router health +
    circuit-breaker states, the in-flight request table and the
    per-replica registries, all in the same JSON."""
    import glob
    import json as json_mod

    from parallax_tpu.obs.flightrec import FlightRecorder

    inj = FaultInjector()
    flight = FlightRecorder(flight_dir=str(tmp_path))
    fleet, _ = _mlp_fleet(faults=inj, flight=flight)
    try:
        inj.arm(0, "crash")
        reqs = [fleet.submit(_feed(i)) for i in range(8)]
        for r in reqs:
            r.result(timeout=30.0)
        retried = [r for r in reqs if len(r.replicas) > 1]
        assert retried
    finally:
        fleet.close()
    dumps = glob.glob(str(tmp_path / "flight_fleet_crash*.json"))
    assert len(dumps) == 1
    doc = json_mod.load(open(dumps[0]))
    assert doc["incident_id"]
    assert doc["detail"]["replica"] == 0
    affected = {a["id"]: a["hops"]
                for a in doc["detail"]["affected_requests"]}
    for r in retried:
        assert affected.get(r.id) == r.replicas, (r.id, affected)
    # correlated sections: router health + circuit state, the
    # in-flight table, fleet aggregates with per-replica serve.*
    states = {row["rid"]: row for row in doc["router"]}
    assert states[0]["state"] == EJECTED and states[0]["dead"]
    assert "circuit" in states[0] and "heartbeat_age_s" in states[0]
    assert isinstance(doc["requests_in_flight"], list)
    assert doc["fleet"]["replicas"]["0"]["serve"]
    # the fleet request records ride along for post-hoc attribution
    assert isinstance(doc["request_records"], list)


# -- the tier-1 chaos guard (subprocess driver) -----------------------------


def test_fleet_chaos_guard():
    """tools/check_fleet_faults.py: with 2 replicas under closed-loop
    load, an injected replica crash and a mid-traffic weight hot-swap
    complete with zero dropped accepted requests, zero late service,
    zero serve-time recompiles on every replica (fresh and swapped),
    bit-identical greedy tokens on failover-retried requests, and a
    flight-recorder artifact naming the fleet_crash incident. Run as a
    subprocess (its own __main__ contract) for the same toolchain-
    crash isolation as the SLO and compile-budget guards."""
    tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "check_fleet_faults.py")
    result = _run_driver_json([sys.executable, tool],
                              check_rc=False, timeout=600.0)
    assert result["ok"], result.get("violations", result)
    assert result["crash"]["retried_requests"] >= 1
    assert result["hotswap"]["hotswaps"] == 2
    assert result["bench"]["recompiles"] == 0
