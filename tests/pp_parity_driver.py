"""Loss-parity proof for a tuner-emitted pp>1 plan, in its OWN process.

Acceptance (ISSUE 18): a pipeline plan trains to the SAME losses as
the pp=1 baseline (4-decimal tolerance). State is initialized on the
pp=1 mesh and resharded onto the pipeline plan (the session's replan
path): on this toolchain, sharding-constrained multi-call RNG init is
sharding-dependent for stacked layer params, so init-then-reshard is
the value-preserving route — the same one the live tuner takes when
it switches plans.

Run in a subprocess by tests/test_pipeline.py: in-process multi-mesh
engine builds + steps are exactly the workload that intermittently
hard-crashes this XLA:CPU toolchain (see tests/mesh_search_driver.py)
— a toolchain abort is a process kill pytest's try/except can never
catch, so isolation turns it into a retryable driver failure instead
of a dead test session.

One process covers BOTH schedules against one shared baseline: at
pp=1 both GPipe and 1F1B reduce to the same sequential microbatch
accumulation, so the baseline is schedule-independent (asserted) and
only needs building once.

Run: python tests/pp_parity_driver.py [schedule ...]
"""

from __future__ import annotations

import json
import os
import sys

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count"
                                 "=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    schedules = sys.argv[1:] or ["gpipe", "1f1b"]
    import jax.numpy as jnp
    import numpy as np

    import parallax_tpu as parallax
    from parallax_tpu.core import mesh as mesh_lib
    from parallax_tpu.models import long_context as lc
    from parallax_tpu.tune.costmodel import Plan

    rng = np.random.default_rng(7)
    batches = [lc.make_batch(rng, 8, 16, 512) for _ in range(3)]

    def run_plan(schedule, plan):
        cfg = lc.tiny_config(num_layers=4, max_len=16,
                             compute_dtype=jnp.float32)
        cfg.parallelism = "pipeline"
        cfg.num_microbatches = 2
        cfg.pipeline_schedule = schedule
        sess, *_ = parallax.parallel_run(
            lc.build_model(cfg),
            parallax_config=parallax.Config(run_option="HYBRID",
                                            search_partitions=False),
            num_partitions=1)
        try:
            sess.prepare(batches[0])    # init on the pp=1 mesh
            if plan is not None:
                sess._build_engine(batches[0], plan)  # reshard, no re-init
                assert mesh_lib.AXIS_PIPE in sess.engine.mesh.axis_names
            return [float(sess.run("loss", feed_dict=b))
                    for b in batches]
        finally:
            sess.close()

    base = run_plan(schedules[0], None)
    pp2 = {s: run_plan(s, Plan(dp=4, tp=1, run_option="HYBRID", pp=2,
                               microbatches=2))
           for s in schedules}
    print(json.dumps({"schedules": schedules, "base": base,
                      "pp2": pp2}))


if __name__ == "__main__":
    main()
