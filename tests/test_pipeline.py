"""Pipeline parallelism numerics: pipelined stages == sequential apply."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parallax_tpu.core import mesh as mesh_lib
from parallax_tpu.ops import pipeline as pp


D = 16


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stacked_params(rng, n_stages):
    return {
        "w": jnp.asarray(
            rng.standard_normal((n_stages, D, D)).astype(np.float32))
        * 0.5,
        "b": jnp.asarray(
            rng.standard_normal((n_stages, D)).astype(np.float32)) * 0.1,
    }


def _sequential(params, x, n_stages):
    for s in range(n_stages):
        x = _stage_fn(jax.tree.map(lambda p: p[s], params), x)
    return x


@pytest.mark.parametrize("n_stages,M", [(2, 4), (4, 4), (4, 8), (8, 4)])
def test_matches_sequential(rng, n_stages, M):
    mesh = mesh_lib.build_mesh(num_partitions=n_stages)
    params = _stacked_params(rng, n_stages)
    r = mesh.shape["repl"]
    B = r * M * 2
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))
    expected = _sequential(params, x, n_stages)
    got = jax.jit(lambda p, x: pp.pipeline_apply(
        _stage_fn, p, x, mesh, M))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-6)


def test_gradients_match_sequential(rng):
    n_stages, M = 4, 4
    mesh = mesh_lib.build_mesh(num_partitions=n_stages)
    params = _stacked_params(rng, n_stages)
    B = mesh.shape["repl"] * M
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))

    def pipe_loss(params, x):
        return jnp.sum(pp.pipeline_apply(_stage_fn, params, x, mesh, M)
                       ** 2)

    def seq_loss(params, x):
        return jnp.sum(_sequential(params, x, n_stages) ** 2)

    gp = jax.jit(jax.grad(pipe_loss))(params, x)
    gs = jax.grad(seq_loss)(params, x)
    for name in ("w", "b"):
        np.testing.assert_allclose(np.asarray(gp[name]),
                                   np.asarray(gs[name]), rtol=5e-5,
                                   atol=5e-6, err_msg=name)


@pytest.mark.parametrize("n_stages,M", [(1, 4), (2, 4), (4, 4), (4, 8)])
def test_1f1b_matches_sequential(rng, n_stages, M):
    """1F1B fused loss+grads (stage, head, AND input cotangent) ==
    sequential forward + autodiff."""
    mesh = mesh_lib.build_mesh(num_partitions=n_stages)
    params = _stacked_params(rng, n_stages)
    head = {"wout": jnp.asarray(
        rng.standard_normal((D, D)).astype(np.float32)) * 0.3}
    B = mesh.shape["repl"] * M * 2
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))

    def mb_loss(head, out, y_mb):
        return jnp.mean((out @ head["wout"] - y_mb) ** 2)

    loss, (g_stage, g_head, g_x) = jax.jit(
        lambda p, h, x, y: pp.pipeline_value_and_grad(
            _stage_fn, mb_loss, p, x, y, mesh, M, head_params=h)
    )(params, head, x, y)

    def seq_loss(params, head, x):
        out = _sequential(params, x, n_stages)
        return jnp.mean((out @ head["wout"] - y) ** 2)

    eloss, (ep, eh, ex) = jax.value_and_grad(seq_loss, argnums=(0, 1, 2))(
        params, head, x)
    np.testing.assert_allclose(float(loss), float(eloss), rtol=2e-5)
    for name in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_stage[name]),
                                   np.asarray(ep[name]), rtol=5e-4,
                                   atol=5e-6, err_msg=name)
    np.testing.assert_allclose(np.asarray(g_head["wout"]),
                               np.asarray(eh["wout"]), rtol=5e-4,
                               atol=5e-6)
    np.testing.assert_allclose(np.asarray(g_x), np.asarray(ex),
                               rtol=5e-4, atol=5e-6)


def _sequential_dm(params, x, S, V):
    """Sequential reference over device-major-stacked [S*V, ...] params:
    global stage g lives at row (g % S)*V + g//S."""
    for g in range(S * V):
        q = (g % S) * V + g // S
        x = _stage_fn(jax.tree.map(lambda p: p[q], params), x)
    return x


@pytest.mark.parametrize("S,V,M", [(2, 2, 4), (4, 2, 4), (2, 3, 4),
                                   (4, 2, 6)])  # 6 % 4: ragged round
def test_interleaved_matches_sequential(rng, S, V, M):
    mesh = mesh_lib.build_mesh(num_partitions=S)
    params = _stacked_params(rng, S * V)
    B = mesh.shape["repl"] * M * 2
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))
    expected = _sequential_dm(params, x, S, V)
    got = jax.jit(lambda p, x: pp.pipeline_apply(
        _stage_fn, p, x, mesh, M, virtual_stages=V))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-6)


def test_interleaved_gradients_match_sequential(rng):
    S, V, M = 4, 2, 4
    mesh = mesh_lib.build_mesh(num_partitions=S)
    params = _stacked_params(rng, S * V)
    B = mesh.shape["repl"] * M
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))

    def pipe_loss(params, x):
        return jnp.sum(pp.pipeline_apply(_stage_fn, params, x, mesh, M,
                                         virtual_stages=V) ** 2)

    def seq_loss(params, x):
        return jnp.sum(_sequential_dm(params, x, S, V) ** 2)

    gp = jax.jit(jax.grad(pipe_loss))(params, x)
    gs = jax.grad(seq_loss)(params, x)
    for name in ("w", "b"):
        # S*V=8-stage tanh chain: float32 summation-order noise is
        # ~3e-5 abs on O(1) gradients; tolerance covers noise only
        np.testing.assert_allclose(np.asarray(gp[name]),
                                   np.asarray(gs[name]), rtol=1e-3,
                                   atol=1e-4, err_msg=name)


@pytest.mark.parametrize("S,V,M", [(2, 2, 4), (4, 2, 4), (2, 3, 6),
                                   (4, 2, 6)])  # 6 % 4: ragged round
def test_interleaved_1f1b_matches_sequential(rng, S, V, M):
    """Interleaved 1F1B fused loss+grads == sequential autodiff."""
    mesh = mesh_lib.build_mesh(num_partitions=S)
    params = _stacked_params(rng, S * V)
    head = {"wout": jnp.asarray(
        rng.standard_normal((D, D)).astype(np.float32)) * 0.3}
    B = mesh.shape["repl"] * M
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))

    def mb_loss(head, out, y_mb):
        return jnp.mean((out @ head["wout"] - y_mb) ** 2)

    loss, (g_stage, g_head, g_x) = jax.jit(
        lambda p, h, x, y: pp.pipeline_value_and_grad(
            _stage_fn, mb_loss, p, x, y, mesh, M, head_params=h,
            virtual_stages=V)
    )(params, head, x, y)

    def seq_loss(params, head, x):
        out = _sequential_dm(params, x, S, V)
        return jnp.mean((out @ head["wout"] - y) ** 2)

    eloss, (ep, eh, ex) = jax.value_and_grad(seq_loss, argnums=(0, 1, 2))(
        params, head, x)
    np.testing.assert_allclose(float(loss), float(eloss), rtol=2e-5)
    for name in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_stage[name]),
                                   np.asarray(ep[name]), rtol=1e-3,
                                   atol=1e-4, err_msg=name)
    np.testing.assert_allclose(np.asarray(g_head["wout"]),
                               np.asarray(eh["wout"]), rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(g_x), np.asarray(ex),
                               rtol=1e-3, atol=1e-4)


def test_stage_order_permutation_roundtrip():
    """Device-major slot q holds global stage (q%V)*S + q//V; the
    permutation is a bijection and identity when V=1."""
    assert pp.stage_order_permutation(4, 1) == [0, 1, 2, 3]
    perm = pp.stage_order_permutation(4, 2)
    assert sorted(perm) == list(range(8))
    # device 0's rows (q=0,1) hold stages 0 and 4 — its two chunks
    assert perm[0] == 0 and perm[1] == 4


def test_1f1b_buffer_is_o_s_not_o_m():
    """The in-flight buffer bound is 2S-1 slots, independent of M."""
    assert pp.inflight_buffer_size(num_stages=4, num_microbatches=64) == 7
    assert pp.inflight_buffer_size(num_stages=2, num_microbatches=128) == 3
    # small-M clamp: never allocate more slots than microbatches
    assert pp.inflight_buffer_size(num_stages=8, num_microbatches=4) == 4


@pytest.mark.slow
@pytest.mark.parametrize("schedule,virtual", [("gpipe", 1), ("1f1b", 1),
                                              ("gpipe", 2), ("1f1b", 2)])
def test_pipeline_lm_through_engine(rng, schedule, virtual):
    """'pipeline' mode (both schedules, interleaved and not): stages
    sharded over 'shard', trajectory matches pure data parallelism
    (same math, pipelined schedule; 1F1B additionally fuses the
    backward via Model.value_and_grad_fn; virtual=2 interleaves two
    chunks per device with device-major layer storage)."""
    import parallax_tpu as parallax
    from parallax_tpu.models import long_context as lc

    batches = [lc.make_batch(rng, 8, 16, 512) for _ in range(3)]
    stages = 4 if virtual == 1 else 2

    def run(parallelism, num_partitions):
        cfg = lc.tiny_config(num_layers=4, max_len=16)
        cfg.parallelism = parallelism
        cfg.num_microbatches = 2
        cfg.pipeline_schedule = schedule
        if parallelism == "pipeline" and virtual > 1:
            cfg.virtual_stages = virtual
            cfg.pipeline_stages = stages
        sess, *_ = parallax.parallel_run(
            lc.build_model(cfg),
            parallax_config=parallax.Config(run_option="HYBRID",
                                            search_partitions=False),
            num_partitions=num_partitions)
        losses = [sess.run("loss", feed_dict=b) for b in batches]
        state = sess.state
        sess.close()
        return losses, state

    pipe_losses, pipe_state = run("pipeline", stages)
    data_losses, _ = run("data", 1)
    # stage params sharded: each device holds num_layers/stages rows
    w = pipe_state.params["blocks_stacked"]["wqkv"]
    assert w.sharding.shard_shape(w.shape)[0] == 4 // stages
    np.testing.assert_allclose(pipe_losses, data_losses, rtol=2e-3)
