"""Pipeline parallelism numerics: pipelined stages == sequential apply."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parallax_tpu.core import mesh as mesh_lib
from parallax_tpu.ops import pipeline as pp


D = 16


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stacked_params(rng, n_stages):
    return {
        "w": jnp.asarray(
            rng.standard_normal((n_stages, D, D)).astype(np.float32))
        * 0.5,
        "b": jnp.asarray(
            rng.standard_normal((n_stages, D)).astype(np.float32)) * 0.1,
    }


def _sequential(params, x, n_stages):
    for s in range(n_stages):
        x = _stage_fn(jax.tree.map(lambda p: p[s], params), x)
    return x


@pytest.mark.parametrize("n_stages,M", [(2, 4), (4, 4), (4, 8), (8, 4)])
def test_matches_sequential(rng, n_stages, M):
    mesh = mesh_lib.build_mesh(num_partitions=n_stages)
    params = _stacked_params(rng, n_stages)
    r = mesh.shape["repl"]
    B = r * M * 2
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))
    expected = _sequential(params, x, n_stages)
    got = jax.jit(lambda p, x: pp.pipeline_apply(
        _stage_fn, p, x, mesh, M))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-6)


def test_gradients_match_sequential(rng):
    n_stages, M = 4, 4
    mesh = mesh_lib.build_mesh(num_partitions=n_stages)
    params = _stacked_params(rng, n_stages)
    B = mesh.shape["repl"] * M
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))

    def pipe_loss(params, x):
        return jnp.sum(pp.pipeline_apply(_stage_fn, params, x, mesh, M)
                       ** 2)

    def seq_loss(params, x):
        return jnp.sum(_sequential(params, x, n_stages) ** 2)

    gp = jax.jit(jax.grad(pipe_loss))(params, x)
    gs = jax.grad(seq_loss)(params, x)
    for name in ("w", "b"):
        np.testing.assert_allclose(np.asarray(gp[name]),
                                   np.asarray(gs[name]), rtol=5e-5,
                                   atol=5e-6, err_msg=name)


@pytest.mark.parametrize("n_stages,M", [(1, 4), (2, 4), (4, 4), (4, 8)])
def test_1f1b_matches_sequential(rng, n_stages, M):
    """1F1B fused loss+grads (stage, head, AND input cotangent) ==
    sequential forward + autodiff."""
    mesh = mesh_lib.build_mesh(num_partitions=n_stages)
    params = _stacked_params(rng, n_stages)
    head = {"wout": jnp.asarray(
        rng.standard_normal((D, D)).astype(np.float32)) * 0.3}
    B = mesh.shape["repl"] * M * 2
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))

    def mb_loss(head, out, y_mb):
        return jnp.mean((out @ head["wout"] - y_mb) ** 2)

    loss, (g_stage, g_head, g_x) = jax.jit(
        lambda p, h, x, y: pp.pipeline_value_and_grad(
            _stage_fn, mb_loss, p, x, y, mesh, M, head_params=h)
    )(params, head, x, y)

    def seq_loss(params, head, x):
        out = _sequential(params, x, n_stages)
        return jnp.mean((out @ head["wout"] - y) ** 2)

    eloss, (ep, eh, ex) = jax.value_and_grad(seq_loss, argnums=(0, 1, 2))(
        params, head, x)
    np.testing.assert_allclose(float(loss), float(eloss), rtol=2e-5)
    for name in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_stage[name]),
                                   np.asarray(ep[name]), rtol=5e-4,
                                   atol=5e-6, err_msg=name)
    np.testing.assert_allclose(np.asarray(g_head["wout"]),
                               np.asarray(eh["wout"]), rtol=5e-4,
                               atol=5e-6)
    np.testing.assert_allclose(np.asarray(g_x), np.asarray(ex),
                               rtol=5e-4, atol=5e-6)


def _sequential_dm(params, x, S, V):
    """Sequential reference over device-major-stacked [S*V, ...] params:
    global stage g lives at row (g % S)*V + g//S."""
    for g in range(S * V):
        q = (g % S) * V + g // S
        x = _stage_fn(jax.tree.map(lambda p: p[q], params), x)
    return x


@pytest.mark.parametrize("S,V,M", [(2, 2, 4), (4, 2, 4), (2, 3, 4),
                                   (4, 2, 6)])  # 6 % 4: ragged round
def test_interleaved_matches_sequential(rng, S, V, M):
    mesh = mesh_lib.build_mesh(num_partitions=S)
    params = _stacked_params(rng, S * V)
    B = mesh.shape["repl"] * M * 2
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))
    expected = _sequential_dm(params, x, S, V)
    got = jax.jit(lambda p, x: pp.pipeline_apply(
        _stage_fn, p, x, mesh, M, virtual_stages=V))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-6)


def test_interleaved_gradients_match_sequential(rng):
    S, V, M = 4, 2, 4
    mesh = mesh_lib.build_mesh(num_partitions=S)
    params = _stacked_params(rng, S * V)
    B = mesh.shape["repl"] * M
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))

    def pipe_loss(params, x):
        return jnp.sum(pp.pipeline_apply(_stage_fn, params, x, mesh, M,
                                         virtual_stages=V) ** 2)

    def seq_loss(params, x):
        return jnp.sum(_sequential_dm(params, x, S, V) ** 2)

    gp = jax.jit(jax.grad(pipe_loss))(params, x)
    gs = jax.grad(seq_loss)(params, x)
    for name in ("w", "b"):
        # S*V=8-stage tanh chain: float32 summation-order noise is
        # ~3e-5 abs on O(1) gradients; tolerance covers noise only
        np.testing.assert_allclose(np.asarray(gp[name]),
                                   np.asarray(gs[name]), rtol=1e-3,
                                   atol=1e-4, err_msg=name)


@pytest.mark.parametrize("S,V,M", [(2, 2, 4), (4, 2, 4), (2, 3, 6),
                                   (4, 2, 6)])  # 6 % 4: ragged round
def test_interleaved_1f1b_matches_sequential(rng, S, V, M):
    """Interleaved 1F1B fused loss+grads == sequential autodiff."""
    mesh = mesh_lib.build_mesh(num_partitions=S)
    params = _stacked_params(rng, S * V)
    head = {"wout": jnp.asarray(
        rng.standard_normal((D, D)).astype(np.float32)) * 0.3}
    B = mesh.shape["repl"] * M
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))

    def mb_loss(head, out, y_mb):
        return jnp.mean((out @ head["wout"] - y_mb) ** 2)

    loss, (g_stage, g_head, g_x) = jax.jit(
        lambda p, h, x, y: pp.pipeline_value_and_grad(
            _stage_fn, mb_loss, p, x, y, mesh, M, head_params=h,
            virtual_stages=V)
    )(params, head, x, y)

    def seq_loss(params, head, x):
        out = _sequential_dm(params, x, S, V)
        return jnp.mean((out @ head["wout"] - y) ** 2)

    eloss, (ep, eh, ex) = jax.value_and_grad(seq_loss, argnums=(0, 1, 2))(
        params, head, x)
    np.testing.assert_allclose(float(loss), float(eloss), rtol=2e-5)
    for name in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_stage[name]),
                                   np.asarray(ep[name]), rtol=1e-3,
                                   atol=1e-4, err_msg=name)
    np.testing.assert_allclose(np.asarray(g_head["wout"]),
                               np.asarray(eh["wout"]), rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(g_x), np.asarray(ex),
                               rtol=1e-3, atol=1e-4)


def test_stage_order_permutation_roundtrip():
    """Device-major slot q holds global stage (q%V)*S + q//V; the
    permutation is a bijection and identity when V=1."""
    assert pp.stage_order_permutation(4, 1) == [0, 1, 2, 3]
    perm = pp.stage_order_permutation(4, 2)
    assert sorted(perm) == list(range(8))
    # device 0's rows (q=0,1) hold stages 0 and 4 — its two chunks
    assert perm[0] == 0 and perm[1] == 4


def test_1f1b_buffer_is_o_s_not_o_m():
    """The in-flight buffer bound is 2S-1 slots, independent of M."""
    assert pp.inflight_buffer_size(num_stages=4, num_microbatches=64) == 7
    assert pp.inflight_buffer_size(num_stages=2, num_microbatches=128) == 3
    # small-M clamp: never allocate more slots than microbatches
    assert pp.inflight_buffer_size(num_stages=8, num_microbatches=4) == 4


# -- the third mesh axis (ISSUE 18): stages on 'pipe', not 'shard' --------


def test_build_mesh_3_tuple_shape_and_validation():
    mesh = mesh_lib.build_mesh(shape=(2, 2, 2))
    assert mesh.axis_names == (mesh_lib.AXIS_REPL, mesh_lib.AXIS_SHARD,
                               mesh_lib.AXIS_PIPE)
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "repl": 2, "shard": 2, "pipe": 2}
    # pp=1 keeps the exact legacy 2-axis mesh — no vestigial axis
    flat = mesh_lib.build_mesh(shape=(4, 2, 1))
    assert flat.axis_names == (mesh_lib.AXIS_REPL, mesh_lib.AXIS_SHARD)
    with pytest.raises(ValueError, match="dp\\*tp\\*pp"):
        mesh_lib.build_mesh(shape=(2, 2, 3))


def test_pipeline_axis_helpers():
    three = mesh_lib.build_mesh(shape=(2, 2, 2))
    two = mesh_lib.build_mesh(shape=(4, 2))
    assert mesh_lib.pipeline_axis(three) == mesh_lib.AXIS_PIPE
    assert mesh_lib.pipeline_axis(two) == mesh_lib.AXIS_SHARD
    assert mesh_lib.pipeline_stage_count(three) == 2
    assert mesh_lib.pipeline_stage_count(two) == 2


def test_resolve_spec_folds_pipe_onto_shard():
    from jax.sharding import PartitionSpec as P
    three = mesh_lib.build_mesh(shape=(2, 2, 2))
    two = mesh_lib.build_mesh(shape=(4, 2))
    spec = P(mesh_lib.AXIS_PIPE)
    # a 3-axis mesh keeps the declared spec; a 2-axis mesh maps the
    # pipeline axis onto 'shard' so one declaration runs on both
    assert mesh_lib.resolve_spec(spec, three) == spec
    assert mesh_lib.resolve_spec(spec, two) == P(mesh_lib.AXIS_SHARD)
    keep = P(mesh_lib.AXIS_REPL, None)
    assert mesh_lib.resolve_spec(keep, two) == keep


def test_pipeline_engine_guard_disables_persistent_cache(monkeypatch,
                                                         tmp_path):
    """Reloading a persistently-cached pipeline-schedule executable
    segfaults this XLA:CPU toolchain (a hard process kill — the
    reason tier-1's pipeline session proofs run in subprocess
    drivers), so the first pipeline engine in a process must switch
    the persistent compilation cache off, once, before any lookup."""
    from parallax_tpu.core import engine as engine_lib

    monkeypatch.setattr(engine_lib, "_pipeline_cache_guarded", False)
    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", str(tmp_path))
    try:
        engine_lib._guard_persistent_cache_for_pipeline()
        assert jax.config.jax_compilation_cache_dir is None
        # one-way per process: once tripped, a later re-enable by the
        # user is respected (the guard never fires twice)
        jax.config.update("jax_compilation_cache_dir", str(tmp_path))
        engine_lib._guard_persistent_cache_for_pipeline()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


@pytest.mark.parametrize("shape", [(2, 2, 2), (4, 1, 2), (2, 1, 4)])
def test_matches_sequential_on_3_axis_mesh(rng, shape):
    """Stages ring over 'pipe'; 'repl' carries data parallelism and
    'shard' runs identical program copies — numerics must match the
    2-axis path exactly."""
    M = 4
    mesh = mesh_lib.build_mesh(shape=shape)
    S = mesh.shape[mesh_lib.AXIS_PIPE]
    params = _stacked_params(rng, S)
    B = mesh.shape["repl"] * M * 2
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))
    expected = _sequential(params, x, S)
    got = jax.jit(lambda p, x: pp.pipeline_apply(
        _stage_fn, p, x, mesh, M))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("shape", [(2, 2, 2), (4, 1, 2), (2, 1, 4)])
def test_1f1b_matches_sequential_on_3_axis_mesh(rng, shape):
    M = 4
    mesh = mesh_lib.build_mesh(shape=shape)
    S = mesh.shape[mesh_lib.AXIS_PIPE]
    params = _stacked_params(rng, S)
    head = {"wout": jnp.asarray(
        rng.standard_normal((D, D)).astype(np.float32)) * 0.3}
    B = mesh.shape["repl"] * M
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))

    def mb_loss(head, out, y_mb):
        return jnp.mean((out @ head["wout"] - y_mb) ** 2)

    loss, (g_stage, g_head, g_x) = jax.jit(
        lambda p, h, x, y: pp.pipeline_value_and_grad(
            _stage_fn, mb_loss, p, x, y, mesh, M, head_params=h)
    )(params, head, x, y)

    def seq_loss(params, head, x):
        out = _sequential(params, x, S)
        return jnp.mean((out @ head["wout"] - y) ** 2)

    eloss, (ep, eh, ex) = jax.value_and_grad(seq_loss, argnums=(0, 1, 2))(
        params, head, x)
    np.testing.assert_allclose(float(loss), float(eloss), rtol=2e-5)
    for name in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_stage[name]),
                                   np.asarray(ep[name]), rtol=5e-4,
                                   atol=5e-6, err_msg=name)
    np.testing.assert_allclose(np.asarray(g_head["wout"]),
                               np.asarray(eh["wout"]), rtol=5e-4,
                               atol=5e-6)
    np.testing.assert_allclose(np.asarray(g_x), np.asarray(ex),
                               rtol=5e-4, atol=5e-6)


def test_ragged_interleaved_warns_once(rng, caplog):
    """M % S != 0 at V > 1 runs masked bubble entries — pure waste the
    user should hear about exactly once per (M, S, V)."""
    import logging
    S, V, M = 2, 2, 3
    mesh = mesh_lib.build_mesh(num_partitions=S)
    params = _stacked_params(rng, S * V)
    B = mesh.shape["repl"] * M
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))
    pp._ragged_warned.discard((M, S, V))
    with caplog.at_level(logging.WARNING, logger="PARALLAX"):
        pp.pipeline_apply(_stage_fn, params, x, mesh, M,
                          virtual_stages=V)
    ragged = [r for r in caplog.records
              if "pads to" in r.getMessage()]
    assert len(ragged) == 1, caplog.records
    # rounded-M figure matches the cost model's pricing
    assert "pads to 4 entries" in ragged[0].getMessage()
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="PARALLAX"):
        pp.pipeline_apply(_stage_fn, params, x, mesh, M,
                          virtual_stages=V)
    assert not [r for r in caplog.records
                if "pads to" in r.getMessage()]
    # aligned schedules never warn
    pp._ragged_warned.discard((4, S, V))
    x4 = jnp.asarray(rng.standard_normal(
        (mesh.shape["repl"] * 4, D)).astype(np.float32))
    with caplog.at_level(logging.WARNING, logger="PARALLAX"):
        pp.pipeline_apply(_stage_fn, params, x4, mesh, 4,
                          virtual_stages=V)
    assert not [r for r in caplog.records
                if "pads to" in r.getMessage()]


def _run_parity_driver(cmd, timeout=480.0, attempts=2):
    """Subprocess driver with crash-retry (the test_tune.py pattern):
    in-process multi-mesh session work intermittently hard-crashes
    this XLA:CPU toolchain, and a toolchain abort is a process kill a
    try/except can never catch — isolation makes a crash cost one
    retry, never the pytest process."""
    import json

    env = dict(os.environ,
               PYTHONPATH=os.pathsep.join(
                   [os.path.join(os.path.dirname(__file__), "..")]
                   + ([os.environ["PYTHONPATH"]]
                      if os.environ.get("PYTHONPATH") else [])),
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    last = None
    for _ in range(attempts):
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=timeout)
        if proc.returncode < 0 or proc.returncode in (134, 139):
            last = (f"driver died with rc={proc.returncode}: "
                    f"{proc.stderr[-500:]}")
            continue
        start = proc.stdout.find("{")
        assert start >= 0, (
            f"driver printed no JSON (rc={proc.returncode}): "
            f"{proc.stdout[-300:]} {proc.stderr[-500:]}")
        result = json.loads(proc.stdout[start:])
        assert proc.returncode == 0, (proc.returncode, result,
                                      proc.stderr[-800:])
        return result
    raise AssertionError(last)


def test_session_pp_plan_loss_parity():
    """Acceptance (ISSUE 18): a tuner-emitted pp>1 plan trains to the
    SAME losses as the pp=1 baseline (4-decimal tolerance), for BOTH
    schedules, proven in one isolated driver process
    (tests/pp_parity_driver.py — the driver's docstring has the
    init-then-reshard numerics contract and the isolation
    rationale)."""
    result = _run_parity_driver(
        [sys.executable,
         os.path.join(os.path.dirname(__file__),
                      "pp_parity_driver.py")])
    assert set(result["pp2"]) == {"gpipe", "1f1b"}
    assert len(result["base"]) == 3
    for schedule, losses in result["pp2"].items():
        np.testing.assert_allclose(losses, result["base"], atol=1e-4,
                                   err_msg=schedule)


@pytest.mark.slow
@pytest.mark.parametrize("schedule,virtual", [("gpipe", 1), ("1f1b", 1),
                                              ("gpipe", 2), ("1f1b", 2)])
def test_pipeline_lm_through_engine(rng, schedule, virtual):
    """'pipeline' mode (both schedules, interleaved and not): stages
    sharded over 'shard', trajectory matches pure data parallelism
    (same math, pipelined schedule; 1F1B additionally fuses the
    backward via Model.value_and_grad_fn; virtual=2 interleaves two
    chunks per device with device-major layer storage)."""
    import parallax_tpu as parallax
    from parallax_tpu.models import long_context as lc

    batches = [lc.make_batch(rng, 8, 16, 512) for _ in range(3)]
    stages = 4 if virtual == 1 else 2

    def run(parallelism, num_partitions):
        cfg = lc.tiny_config(num_layers=4, max_len=16)
        cfg.parallelism = parallelism
        cfg.num_microbatches = 2
        cfg.pipeline_schedule = schedule
        if parallelism == "pipeline" and virtual > 1:
            cfg.virtual_stages = virtual
            cfg.pipeline_stages = stages
        sess, *_ = parallax.parallel_run(
            lc.build_model(cfg),
            parallax_config=parallax.Config(run_option="HYBRID",
                                            search_partitions=False),
            num_partitions=num_partitions)
        losses = [sess.run("loss", feed_dict=b) for b in batches]
        state = sess.state
        sess.close()
        return losses, state

    pipe_losses, pipe_state = run("pipeline", stages)
    data_losses, _ = run("data", 1)
    # stage params sharded: each device holds num_layers/stages rows
    w = pipe_state.params["blocks_stacked"]["wqkv"]
    assert w.sharding.shard_shape(w.shape)[0] == 4 // stages
    np.testing.assert_allclose(pipe_losses, data_losses, rtol=2e-3)
