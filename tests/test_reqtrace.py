"""Serving forensics (ISSUE 12): per-request lifecycle records (phase
decomposition that sums to client TTFT, failover accumulation, KV
pages, hop trails), the bounded request ring with lazy serve.timeline.*
/ serve.slo.* gauges and chrome lanes keyed by request id, the
Prometheus telemetry exporter, and the percentile attribution report
(tools/serve_report.py) including the 64-offered acceptance level."""

import json
import time
import urllib.request

import numpy as np
import pytest

from parallax_tpu import obs
from parallax_tpu.obs.metrics import MetricsRegistry
from parallax_tpu.obs.reqtrace import (PHASES, RequestRecord,
                                       RequestTraceRing)


# -- the phase state machine ------------------------------------------------


class TestRequestRecord:
    def test_phases_partition_the_wall_clock(self):
        rec = RequestRecord(key=1, t0=100.0)
        rec.mark("queue_wait", now=100.010)
        rec.mark("prefill", now=100.030)
        rec.mark("decode", now=100.050)
        rec.complete(now=100.100)
        assert rec.phases["admission"] == pytest.approx(10.0)
        assert rec.phases["queue_wait"] == pytest.approx(20.0)
        assert rec.phases["prefill"] == pytest.approx(20.0)
        assert rec.phases["decode"] == pytest.approx(50.0)
        assert rec.total_ms == pytest.approx(100.0)
        # the partition property: phases sum to the full window
        assert sum(rec.phases.values()) == pytest.approx(rec.total_ms)

    def test_ttft_decomp_sums_to_ttft_exactly(self):
        rec = RequestRecord(key=2, t0=0.0)
        rec.mark("queue_wait", now=0.005)
        rec.mark("prefill", now=0.020)
        rec.mark("decode", now=0.030)
        rec.first_token(now=0.045)      # mid-decode snapshot
        rec.complete(now=0.090)
        assert rec.ttft_ms == pytest.approx(45.0)
        assert sum(rec.ttft_decomp.values()) \
            == pytest.approx(rec.ttft_ms)
        # the open decode phase's in-progress share is included
        assert rec.ttft_decomp["decode_ms"] == pytest.approx(15.0)
        # ...without having closed it: decode keeps accruing to done
        assert rec.phases["decode"] == pytest.approx(60.0)

    def test_failover_accumulates_one_record_across_hops(self):
        rec = RequestRecord(key=3, t0=0.0, fleet_owned=True)
        rec.note_hop(0)
        rec.mark("queue_wait", now=0.010)
        rec.mark("prefill", now=0.020)
        # replica 0 dies mid-prefill: fleet-owned records stay OPEN
        rec.attempt_failed("ReplicaUnavailable", now=0.030)
        assert not rec.done
        rec.mark("failover", now=0.030)
        rec.note_retry()
        rec.note_hop(1)
        rec.mark("queue_wait", now=0.040)   # re-placed on replica 1
        rec.mark("prefill", now=0.050)
        rec.mark("decode", now=0.070)
        rec.first_token(now=0.080)
        rec.complete(now=0.100)
        assert rec.hops == [0, 1]
        assert rec.retries == 1
        assert rec.phases["failover"] == pytest.approx(10.0)
        # re-entered phases accumulate: 10ms + 10ms of queue_wait
        assert rec.phases["queue_wait"] == pytest.approx(20.0)
        assert sum(rec.ttft_decomp.values()) \
            == pytest.approx(rec.ttft_ms) == pytest.approx(80.0)

    def test_refused_placement_retracts_the_hop(self):
        """A replica that sheds at admission never held the request:
        the announced hop is retracted, keeping the trail consistent
        with the fleet's replicas-actually-placed-on list (and the
        incident dump's affected-set matching)."""
        rec = RequestRecord(key=30, t0=0.0, fleet_owned=True)
        rec.note_hop(0)
        rec.drop_hop()          # replica 0 shed at queue.put
        assert rec.hops == []
        rec.drop_hop()          # empty trail: no-op, no IndexError
        rec.note_hop(1)
        rec.complete(now=0.010)
        assert rec.hops == [1]

    def test_standalone_attempt_failure_finalizes(self):
        rec = RequestRecord(key=4, t0=0.0)
        rec.mark("queue_wait", now=0.010)
        rec.attempt_failed("ReplicaUnavailable", now=0.020)
        assert rec.done and rec.outcome == "ReplicaUnavailable"

    def test_completion_is_idempotent_first_wins(self):
        rec = RequestRecord(key=5, t0=0.0)
        rec.complete(now=0.010, outcome="completed")
        rec.complete(now=0.500, outcome="failed:late")
        assert rec.outcome == "completed"
        assert rec.total_ms == pytest.approx(10.0)

    def test_disabled_layer_records_nothing(self):
        obs.disable()
        try:
            rec = RequestRecord(key=6, t0=0.0)
            rec.mark("queue_wait", now=0.010)
            rec.note_hop(0)
            rec.first_token(now=0.020)
            rec.complete(now=0.030)
        finally:
            obs.enable()
        assert rec.phases == {} and rec.hops == []
        assert rec.ttft_ms is None and not rec.done

    def test_segments_bounded(self):
        rec = RequestRecord(key=7, t0=0.0)
        for i in range(500):
            rec.mark("decode" if i % 2 else "prefill", now=i * 1e-3)
        assert len(rec.segments) <= RequestRecord.MAX_SEGMENTS
        # accumulation continues past the segment cap
        assert rec.n_marks == 500

    def test_missed_deadline_flag(self):
        rec = RequestRecord(key=8, t0=0.0, deadline=0.050)
        rec.complete(now=0.080)
        assert rec.missed_deadline() is True
        rec2 = RequestRecord(key=9, t0=0.0, deadline=0.050)
        rec2.complete(now=0.010)
        assert rec2.missed_deadline() is False
        assert RequestRecord(key=10, t0=0.0).missed_deadline() is None


# -- the ring + lazy gauges -------------------------------------------------


def _completed_record(key, t0=0.0, queue=0.010, decode=0.040,
                      deadline=None, outcome="completed"):
    rec = RequestRecord(key=key, t0=t0, deadline=deadline)
    rec.mark("queue_wait", now=t0 + 0.001)
    rec.mark("decode", now=t0 + 0.001 + queue)
    rec.first_token(now=t0 + 0.001 + queue + decode / 2)
    rec.complete(now=t0 + 0.001 + queue + decode, outcome=outcome)
    return rec


class TestRequestTraceRing:
    def test_gauges_sampled_lazily_at_snapshot(self):
        reg = MetricsRegistry()
        ring = RequestTraceRing(reg, capacity=8)
        for i in range(4):
            ring.add(_completed_record(i))
        snap = reg.snapshot()
        assert snap["serve.timeline.requests"] == 4
        assert snap["serve.timeline.queue_wait_ms"]["count"] == 4
        assert snap["serve.timeline.queue_wait_ms"]["mean"] \
            == pytest.approx(10.0, rel=1e-3)
        assert snap["serve.timeline.decode_ms"]["mean"] \
            == pytest.approx(40.0, rel=1e-3)
        assert snap["serve.timeline.ttft_ms"]["count"] == 4
        # phases never entered read as None, not fabricated zeros
        assert snap["serve.timeline.slot_wait_ms"] is None
        json.loads(json.dumps(snap))  # JSON-ready end to end

    def test_ring_bounded_lifetime_counted(self):
        ring = RequestTraceRing(MetricsRegistry(), capacity=4)
        for i in range(10):
            ring.add(_completed_record(i))
        assert ring.total == 10
        recs = ring.records()
        assert len(recs) == 4
        assert recs[-1]["id"] == 9

    def test_slo_burn_gauges(self):
        reg = MetricsRegistry()
        ring = RequestTraceRing(reg, capacity=32, slo_budget=0.01)
        # 8 with deadlines: 2 missed -> miss rate 0.25, budget x25
        for i in range(6):
            ring.add(_completed_record(i, deadline=1.0))
        for i in range(2):
            ring.add(_completed_record(10 + i, deadline=0.001,
                                       outcome="deadline_exceeded"))
        shed = RequestRecord(key=99, t0=0.0)
        shed.complete(now=0.001, outcome="shed")
        ring.add(shed)
        snap = reg.snapshot()
        assert snap["serve.slo.deadline_miss_rate"] \
            == pytest.approx(0.25)
        assert snap["serve.slo.deadline_miss_budget_consumed"] \
            == pytest.approx(25.0)
        assert snap["serve.slo.shed_rate"] == pytest.approx(1 / 9,
                                                           rel=1e-2)
        assert snap["serve.slo.p99_deadline_margin_ms"] < 0  # missed

    def test_chrome_lanes_keyed_by_request(self, tmp_path):
        ring = RequestTraceRing(MetricsRegistry(), capacity=8)
        ring.add(_completed_record("a"))
        ring.add(_completed_record("b"))
        path = tmp_path / "lanes" / "req.json"
        ring.export_chrome_trace(str(path))
        doc = json.loads(path.read_text())
        metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        # one labeled lane per request, phases as complete events
        assert {m["args"]["name"] for m in metas} \
            == {"req a (completed)", "req b (completed)"}
        assert len({m["tid"] for m in metas}) == 2
        lanes = {e["tid"] for e in xs}
        assert lanes == {m["tid"] for m in metas}
        assert {e["name"] for e in xs} \
            <= {"admission", "queue_wait", "decode"}
        assert all(e["args"]["request"] in ("a", "b") for e in xs)

    def test_disabled_ring_collects_nothing(self):
        ring = RequestTraceRing(MetricsRegistry(), capacity=8)
        rec = _completed_record(0)   # completed while enabled
        obs.disable()
        try:
            ring.add(rec)
        finally:
            obs.enable()
        assert ring.total == 0


# -- the telemetry exporter -------------------------------------------------


class TestTelemetryExporter:
    def _get(self, url):
        with urllib.request.urlopen(url, timeout=10.0) as resp:
            return resp.status, resp.headers.get("Content-Type"), \
                resp.read().decode()

    def test_prometheus_endpoint_renders_registries(self):
        reg = MetricsRegistry()
        reg.counter("serve.completed").inc(7)
        reg.gauge("serve.queue_depth").set(3)
        h = reg.histogram("serve.ttft_ms")
        for v in (10.0, 20.0, 30.0):
            h.record(v)
        ring = RequestTraceRing(reg, capacity=8)
        ring.add(_completed_record(0, deadline=1.0))
        exporter = obs.TelemetryExporter(
            lambda: {"fleet": reg.snapshot()})
        try:
            exporter.start()
            status, ctype, body = self._get(exporter.url)
        finally:
            exporter.stop()
        assert status == 200 and "text/plain" in ctype
        assert 'parallax_serve_completed{source="fleet"} 7.0' in body
        assert 'parallax_serve_queue_depth{source="fleet"} 3.0' in body
        # histograms expand to _count/_mean/_max + quantile samples
        assert 'parallax_serve_ttft_ms_count{source="fleet"} 3.0' \
            in body
        assert ('parallax_serve_ttft_ms{source="fleet",'
                'quantile="0.5"} 20.0') in body
        # the lazy request-timeline and SLO burn gauges ride along
        assert "parallax_serve_timeline_decode_ms_mean" in body
        assert "parallax_serve_slo_deadline_miss_rate" in body

    def test_healthz_and_unknown_path(self):
        exporter = obs.TelemetryExporter(lambda: {"": {}})
        try:
            exporter.start()
            base = exporter.url.rsplit("/", 1)[0]
            status, _, body = self._get(base + "/healthz")
            assert status == 200 and json.loads(body) == {"ok": True}
            with pytest.raises(urllib.error.HTTPError):
                self._get(base + "/nope")
        finally:
            exporter.stop()
        exporter.stop()  # idempotent

    def test_broken_snapshot_returns_500_not_crash(self):
        def boom():
            raise RuntimeError("poisoned registry")
        exporter = obs.TelemetryExporter(boom)
        try:
            exporter.start()
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(exporter.url)
            assert ei.value.code == 500
            # the server survives a failed scrape
            with pytest.raises(urllib.error.HTTPError):
                self._get(exporter.url)
        finally:
            exporter.stop()


# -- live serving integration -----------------------------------------------


class TestServeIntegration:
    @pytest.fixture(scope="class")
    def decode_session(self):
        from tools import loadgen
        sess, make_feed = loadgen.demo_decode_session(
            slots=4, T=8, Ts=6, model_dim=16, vocab=64,
            speculative=False, prefill_chunk_layers=None)
        yield sess, make_feed
        sess.close()

    def test_records_decompose_ttft_and_name_pages(self,
                                                   decode_session):
        sess, make_feed = decode_session
        reqs = [sess.submit(make_feed(i)) for i in range(6)]
        for r in reqs:
            r.result(timeout=60.0)
        recs = {r["id"]: r for r in sess.request_records()}
        for req in reqs:
            rec = recs[req.id]
            assert rec["outcome"] == "completed"
            for phase in ("admission_ms", "queue_wait_ms",
                          "prefill_ms", "decode_ms"):
                assert phase in rec["phases_ms"], rec
            # paged program: pages held are on the record
            assert rec["kv_pages"] >= 1
            assert rec["decode_steps"] == rec["tokens"] > 0
            # the acceptance property: decomposition sums to the
            # client-side TTFT
            client_ttft_ms = (req.t_first_token - req.t_enqueue) * 1e3
            # snapshot values are rounded to 4 decimals; the raw sum
            # is exact by construction
            assert sum(rec["ttft_decomp"].values()) \
                == pytest.approx(rec["ttft_ms"], abs=0.01)
            assert rec["ttft_ms"] == pytest.approx(client_ttft_ms,
                                                   rel=0.05)
        snap = sess.metrics.snapshot()
        assert snap["serve.timeline.ttft_ms"]["count"] >= 6
        assert snap["serve.timeline.requests"] >= 6

    def test_deadline_expiry_lands_in_slo_gauges(self, decode_session):
        sess, make_feed = decode_session
        req = sess.submit(make_feed(0), deadline_ms=0.01)
        with pytest.raises(Exception):
            req.result(timeout=60.0)
        # wait for the scheduler to process the expiry
        end = time.perf_counter() + 10.0
        while time.perf_counter() < end:
            recs = sess.request_records()
            if any(r["outcome"] == "deadline_exceeded" for r in recs):
                break
            time.sleep(0.01)
        snap = sess.metrics.snapshot()
        assert snap["serve.slo.deadline_miss_rate"] > 0
        assert snap["serve.slo.deadline_miss_budget_consumed"] > 0


# -- the attribution report (tools/serve_report.py) -------------------------


class TestServeReport:
    @staticmethod
    def _fake(ttft, queue, decode, total=None):
        return {"ttft_ms": ttft, "total_ms": total or ttft + 10.0,
                "ttft_decomp": {"queue_wait_ms": queue,
                                "decode_ms": decode}}

    def test_analyze_names_dominant_cause_per_bucket(self):
        from tools import serve_report
        records = (
            # typical half: decode-bound
            [self._fake(10.0, 2.0, 8.0) for _ in range(50)]
            # the tail: queue-bound (the story p99 must tell)
            + [self._fake(100.0 + i, 90.0 + i, 10.0)
               for i in range(10)])
        report = serve_report.analyze(records)
        assert report["requests_analyzed"] == 60
        assert report["buckets"]["p50"]["dominant"] == "decode"
        assert report["dominant_p99"] == "queue_wait"
        assert report["buckets"]["p99"]["ttft_ms"] >= 100.0
        assert "queue_wait" in serve_report.headline(report, 64)

    def test_shares_and_budget_helpers(self):
        from tools import serve_report
        records = [self._fake(10.0, 5.0, 5.0)]
        shares = serve_report.ttft_shares(records)
        assert shares == {"decode_share": 0.5, "queue_wait_share": 0.5}
        assert serve_report.ttft_shares([]) is None
        with_ddl = [dict(self._fake(10.0, 5.0, 5.0), deadline_ms=5.0),
                    dict(self._fake(10.0, 5.0, 5.0), deadline_ms=500.0)]
        assert serve_report.deadline_miss_budget_consumed(
            with_ddl, budget=0.01) == pytest.approx(50.0)
        assert serve_report.deadline_miss_budget_consumed([]) is None

    def test_64_offered_level_names_a_p99_cause(self):
        """Acceptance (ISSUE 12): the serve report at the 64-offered
        sweep level names a dominant p99 cause (small-model rig keeps
        this tier-1-affordable; the phase label is workload-dependent,
        so what is asserted is that ONE valid phase is named with
        self-consistent shares)."""
        from tools import serve_report
        out = serve_report.measure(level=64, requests=96, T=8,
                                   model_dim=16, vocab=64)
        assert out["completed"] == 96
        report = out["report"]
        assert report["dominant_p99"] in PHASES
        p99 = report["buckets"]["p99"]
        assert p99["count"] >= 1 and p99["ttft_ms"] > 0
        assert sum(p99["shares"].values()) == pytest.approx(1.0,
                                                            abs=0.01)
        assert "p99 is" in out["headline"] and "64" in out["headline"]
        assert out["ttft_decomp"]


# -- the fleet exporter convenience -----------------------------------------


def test_fleet_start_exporter_aggregates_replicas():
    from parallax_tpu.serve import FleetConfig, ServeFleet

    class _FakeSession:
        alive = True

        def __init__(self):
            self.heartbeat = time.perf_counter()

        def load(self):
            return 0.0

        def idle(self):
            return True

        def close(self, drain=True):
            pass

    def make_replica(rid, **kw):
        # a real ServeSession fills its registry; the fake seeds one
        # counter so the per-replica source labels are observable
        kw["metrics"].counter("serve.completed").inc(1)
        return _FakeSession()

    fleet = ServeFleet(make_replica,
                       config=FleetConfig(num_replicas=2,
                                          tick_interval_s=3600.0))
    try:
        exporter = fleet.start_exporter()
        with urllib.request.urlopen(exporter.url, timeout=10.0) as r:
            body = r.read().decode()
        assert 'parallax_fleet_replicas{source="fleet"} 2.0' in body
        # per-replica registries are source-labeled in the same scrape
        assert 'source="replica0"' in body
        assert 'source="replica1"' in body
    finally:
        fleet.close()
    assert fleet._exporter._server is None  # stopped at close
