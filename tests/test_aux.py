"""Checkpoint, profiler, and partition-search-in-session tests."""

import glob
import os

import numpy as np
import pytest

import parallax_tpu as parallax
from parallax_tpu.models import simple


def _run_steps(sess, rng, n, bs=64):
    out = None
    for _ in range(n):
        b = simple.make_batch(rng, bs)
        out = sess.run(["loss", "global_step"], feed_dict=b)
    return out


class TestCheckpoint:
    def test_save_and_restore_resumes_step(self, tmp_path, rng):
        ckpt_dir = str(tmp_path / "ckpt")
        cfg = parallax.Config(
            run_option="AR", search_partitions=False,
            ckpt_config=parallax.CheckPointConfig(ckpt_dir=ckpt_dir,
                                                  save_ckpt_steps=5))
        model = simple.build_model(0.1)
        sess, *_ = parallax.parallel_run(model, parallax_config=cfg)
        loss1, step1 = _run_steps(sess, rng, 12)
        w_before = np.asarray(sess.state.params["w"])
        sess.close()
        assert step1 == 12

        # New session restores the latest checkpoint (step 10) like
        # MonitoredTrainingSession restore-from-checkpoint_dir.
        sess2, *_ = parallax.parallel_run(simple.build_model(0.1),
                                          parallax_config=cfg)
        _, step2 = _run_steps(sess2, rng, 1)
        assert step2 == 11  # resumed from 10
        sess2.close()

    def test_async_save_knob_roundtrips(self, tmp_path, rng):
        """CheckPointConfig.async_save=True (opt-in since r5; the
        default is synchronous for reference durability parity): the
        background commit must be awaited by close() and restore
        identically."""
        ckpt_dir = str(tmp_path / "ckpt_async")
        cfg = parallax.Config(
            run_option="AR", search_partitions=False,
            ckpt_config=parallax.CheckPointConfig(ckpt_dir=ckpt_dir,
                                                  save_ckpt_steps=4,
                                                  async_save=True))
        sess, *_ = parallax.parallel_run(simple.build_model(0.1),
                                         parallax_config=cfg)
        _run_steps(sess, rng, 8)
        sess.close()  # waits for the background commit
        sess2, *_ = parallax.parallel_run(simple.build_model(0.1),
                                          parallax_config=cfg)
        _, step = _run_steps(sess2, rng, 1)
        assert step == 9  # resumed from the async step-8 save
        sess2.close()

    def test_sync_save_knob_roundtrips(self, tmp_path, rng):
        """CheckPointConfig.async_save=False: fully synchronous saves
        (reference behavior) write and restore identically."""
        ckpt_dir = str(tmp_path / "ckpt_sync")
        cfg = parallax.Config(
            run_option="AR", search_partitions=False,
            ckpt_config=parallax.CheckPointConfig(ckpt_dir=ckpt_dir,
                                                  save_ckpt_steps=4,
                                                  async_save=False))
        sess, *_ = parallax.parallel_run(simple.build_model(0.1),
                                         parallax_config=cfg)
        _run_steps(sess, rng, 8)
        sess.close()
        sess2, *_ = parallax.parallel_run(simple.build_model(0.1),
                                          parallax_config=cfg)
        _, step = _run_steps(sess2, rng, 1)
        assert step == 9  # resumed from the synchronous step-8 save
        sess2.close()

    def test_save_every_n_steps(self, tmp_path, rng):
        ckpt_dir = str(tmp_path / "ckpt2")
        cfg = parallax.Config(
            run_option="AR", search_partitions=False,
            ckpt_config=parallax.CheckPointConfig(ckpt_dir=ckpt_dir,
                                                  save_ckpt_steps=3))
        sess, *_ = parallax.parallel_run(simple.build_model(0.1),
                                         parallax_config=cfg)
        _run_steps(sess, rng, 7)
        sess.close()
        steps = sorted(int(os.path.basename(p)) for p in
                       glob.glob(os.path.join(ckpt_dir, "*"))
                       if os.path.basename(p).isdigit())
        assert steps == [3, 6]


class TestProfiler:
    def test_profile_steps_write_trace(self, tmp_path, rng):
        prof_dir = str(tmp_path / "prof")
        cfg = parallax.Config(
            run_option="AR", search_partitions=False,
            profile_config=parallax.ProfileConfig(profile_dir=prof_dir,
                                                  profile_steps=[2]))
        sess, *_ = parallax.parallel_run(simple.build_model(0.1),
                                         parallax_config=cfg)
        _run_steps(sess, rng, 4)
        sess.close()
        traces = glob.glob(os.path.join(prof_dir, "**", "*.xplane.pb"),
                           recursive=True)
        assert traces, f"no xplane trace written under {prof_dir}"

    def test_profile_worker_gating(self, tmp_path, rng):
        prof_dir = str(tmp_path / "prof2")
        cfg = parallax.Config(
            run_option="AR", search_partitions=False,
            profile_config=parallax.ProfileConfig(profile_dir=prof_dir,
                                                  profile_steps=[1],
                                                  profile_worker=3))
        sess, *_ = parallax.parallel_run(simple.build_model(0.1),
                                         parallax_config=cfg)
        _run_steps(sess, rng, 3)
        sess.close()
        assert not os.path.exists(prof_dir)  # we are worker 0, not 3


class TestPartitionSearchInSession:
    def test_search_replans_live(self, rng, monkeypatch):
        """Partition search rebuilds the engine in place (the reference
        kills and relaunches the cluster, partitions.py:74-138)."""
        import jax
        import jax.numpy as jnp
        import optax
        from parallax_tpu.common import consts as c
        from parallax_tpu.core import mesh as mesh_lib
        from parallax_tpu.ops import embedding as emb_ops

        # shrink the timing window so the test is fast
        monkeypatch.setattr(c, "NUM_ITERATIONS_FOR_WARMUP", 1)
        monkeypatch.setattr(c, "NUM_ITERATIONS_FOR_TEST", 3)
        monkeypatch.setenv(c.PARALLAX_MIN_PARTITIONS, "1")

        V, D = 32, 8

        def init_fn(rng_):
            return {"emb": jax.random.normal(rng_, (V, D)) * 0.1}

        def loss_fn(params, batch):
            rows = emb_ops.embedding_lookup(params["emb"], batch["ids"])
            return jnp.mean(rows ** 2)

        model = parallax.Model(init_fn, loss_fn, optimizer=optax.sgd(0.1))
        sess, *_ = parallax.parallel_run(
            model, parallax_config=parallax.Config(run_option="HYBRID"))
        seen_p = set()
        for _ in range(40):
            sess.run("loss", feed_dict={
                "ids": rng.integers(0, V, (16,)).astype(np.int32)})
            seen_p.add(mesh_lib.num_shards(sess.engine.mesh))
            if sess._search is None:
                break
        assert sess._search is None, "search did not converge"
        assert len(seen_p) >= 2, f"search never changed p: {seen_p}"
        sess.close()


class TestMoreTriggers:
    def test_profile_range_traces_span(self, tmp_path, rng):
        prof_dir = str(tmp_path / "prof_range")
        cfg = parallax.Config(
            run_option="AR", search_partitions=False,
            profile_config=parallax.ProfileConfig(profile_dir=prof_dir,
                                                  profile_range=(2, 4)))
        sess, *_ = parallax.parallel_run(simple.build_model(0.1),
                                         parallax_config=cfg)
        _run_steps(sess, rng, 6)
        sess.close()
        traces = glob.glob(os.path.join(prof_dir, "**", "*.xplane.pb"),
                           recursive=True)
        assert traces, "profile_range produced no trace"

    def test_restore_with_explicit_shardings(self, tmp_path, rng, caplog):
        """Eval restore carries explicit shardings on every template
        leaf: with an example_batch the live plan's layout (row-sharded
        tables), otherwise replicated — never Orbax's restore-as-saved
        fallback (which warns it is unsafe across topologies)."""
        import logging
        from parallax_tpu.checkpoint import restore_train_state
        from parallax_tpu.models import lm1b
        ckpt_dir = str(tmp_path / "ckpt_sharded")
        cfg_m = lm1b.tiny_config(num_partitions=8)
        model = lm1b.build_model(cfg_m)
        sess, *_ = parallax.parallel_run(
            model, parallax_config=parallax.Config(
                run_option="HYBRID", search_partitions=False,
                ckpt_config=parallax.CheckPointConfig(
                    ckpt_dir=ckpt_dir, save_ckpt_steps=2)))
        batch = lm1b.make_batch(rng, 16, 8, cfg_m.vocab_size)
        sess.run("loss", feed_dict=batch)
        sess.run("loss", feed_dict=batch)
        sess.close()

        with caplog.at_level(logging.WARNING):
            # plan-derived layout: table comes back row-sharded
            restored, step = restore_train_state(
                ckpt_dir, lm1b.build_model(cfg_m), example_batch=batch)
            assert step == 2
            emb = restored.params["emb"]
            assert not emb.sharding.is_fully_replicated
            assert emb.sharding.shard_shape(emb.shape)[0] == \
                emb.shape[0] // 8
            # default: explicit replicated layout
            restored2, _ = restore_train_state(ckpt_dir,
                                               lm1b.build_model(cfg_m))
            assert restored2.params["emb"].sharding.is_fully_replicated
        assert "sharding" not in " ".join(
            r.message for r in caplog.records
            if r.levelno >= logging.WARNING).lower()

    def test_restore_async_checkpoint(self, tmp_path, rng):
        """sync=False checkpoints carry pending_grads; the eval-flow
        restore must handle both sync and async state shapes."""
        from parallax_tpu.checkpoint import restore_train_state
        ckpt_dir = str(tmp_path / "ckpt_async")
        model = simple.build_model(0.1)
        cfg = parallax.Config(
            run_option="AR", search_partitions=False,
            ckpt_config=parallax.CheckPointConfig(ckpt_dir=ckpt_dir,
                                                  save_ckpt_steps=2))
        sess, *_ = parallax.parallel_run(model, None, sync=False,
                                         parallax_config=cfg)
        _run_steps(sess, rng, 2)
        sess.close()
        restored, step = restore_train_state(ckpt_dir,
                                             simple.build_model(0.1))
        assert step == 2
        assert restored.pending_grads is not None
        assert np.asarray(restored.params["w"]).shape == \
            np.asarray(restored.pending_grads["w"]).shape

    def test_restore_async_checkpoint_staleness_k(self, tmp_path, rng):
        """staleness=k checkpoints carry a [k, ...] gradient ring; the
        restore fallback must rebuild that layout (config carries k)."""
        from parallax_tpu.checkpoint import restore_train_state
        ckpt_dir = str(tmp_path / "ckpt_async_k")
        model = simple.build_model(0.1)
        cfg = parallax.Config(
            run_option="AR", search_partitions=False, staleness=2,
            ckpt_config=parallax.CheckPointConfig(ckpt_dir=ckpt_dir,
                                                  save_ckpt_steps=2))
        sess, *_ = parallax.parallel_run(model, None, sync=False,
                                         parallax_config=cfg)
        _run_steps(sess, rng, 2)
        sess.close()
        restored, step = restore_train_state(
            ckpt_dir, simple.build_model(0.1),
            config=parallax.Config(run_option="AR",
                                   search_partitions=False, staleness=2))
        assert step == 2
        w_shape = np.asarray(restored.params["w"]).shape
        assert np.asarray(restored.pending_grads["w"]).shape == \
            (2,) + w_shape

    def test_secs_trigger_is_broadcast_multiprocess(self, tmp_path,
                                                    monkeypatch):
        """Secs-due is decided by process 0 and broadcast: a host whose
        local clock disagrees must follow the broadcast bit, never its
        own wall clock (else it hangs the Orbax commit barrier)."""
        import jax
        from jax.experimental import multihost_utils
        from parallax_tpu import checkpoint as ckpt_lib

        hook = ckpt_lib.CheckpointHook(
            parallax.CheckPointConfig(ckpt_dir=str(tmp_path / "c"),
                                      save_ckpt_secs=3600.0),
            worker_id=0)
        calls = []

        def fake_broadcast(x):
            calls.append(int(np.asarray(x)))
            return np.asarray(fake_broadcast.chief_due, np.int32)

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(multihost_utils, "broadcast_one_to_all",
                            fake_broadcast)
        k = hook.SECS_BROADCAST_EVERY
        # off-cadence step: no collective at all, even if local clock due
        hook._last_save_time -= 7200.0
        fake_broadcast.chief_due = 1
        assert hook._decide_due(step=k + 1) is False
        assert calls == [], "off-cadence step must not enter a collective"
        # on-cadence, local clock says due but chief says not -> no save
        fake_broadcast.chief_due = 0
        assert hook._decide_due(step=k) is False
        assert calls == [1]
        # on-cadence, local clock NOT due but chief says due -> must save
        hook._last_save_time = __import__("time").time()
        fake_broadcast.chief_due = 1
        assert hook._decide_due(step=2 * k) is True
        assert calls == [1, 0]
        hook.close()

    def test_save_ckpt_secs_trigger(self, tmp_path, rng):
        import time
        ckpt_dir = str(tmp_path / "ckpt_secs")
        cfg = parallax.Config(
            run_option="AR", search_partitions=False,
            ckpt_config=parallax.CheckPointConfig(ckpt_dir=ckpt_dir,
                                                  save_ckpt_secs=1.0))
        sess, *_ = parallax.parallel_run(simple.build_model(0.1),
                                         parallax_config=cfg)
        _run_steps(sess, rng, 2)
        time.sleep(1.2)
        _run_steps(sess, rng, 1)  # secs trigger fires here
        sess.close()
        steps = [int(os.path.basename(p)) for p in
                 glob.glob(os.path.join(ckpt_dir, "*"))
                 if os.path.basename(p).isdigit()]
        assert steps, "secs trigger never saved"


class TestPrepare:
    def test_prepare_builds_without_stepping_and_reports_restore(
            self, tmp_path, rng):
        """Session.prepare(): engine + checkpoint restore without a
        training step — fresh session reports 0, restored session the
        checkpointed step, and state/mesh are readable before step 1
        (the elastic-resume seeding contract, r5)."""
        ckpt_dir = str(tmp_path / "ckpt_prep")
        cfg = parallax.Config(
            run_option="AR", search_partitions=False,
            ckpt_config=parallax.CheckPointConfig(ckpt_dir=ckpt_dir,
                                                  save_ckpt_steps=3))
        batch = simple.make_batch(rng, 32)
        sess, *_ = parallax.parallel_run(simple.build_model(0.1),
                                         parallax_config=cfg)
        assert sess.prepare(batch) == 0          # fresh run
        assert int(sess.state.step) == 0         # no step ran
        assert sess.engine is not None
        _run_steps(sess, rng, 6)                 # ckpts at 3 and 6
        sess.close()

        sess2, *_ = parallax.parallel_run(simple.build_model(0.1),
                                          parallax_config=cfg)
        assert sess2.prepare(batch) == 6         # restored, still no step
        _, step = _run_steps(sess2, rng, 1)
        assert step == 7
        sess2.close()
