"""Ops observatory (ISSUE 20): event journal (causal ring + rotating
JSONL sink), goodput/badput ledger (sum-to-wall by construction,
rollback refunds, cross-attempt persistence through checkpoint
extras), the declarative alert engine lifecycle under fake clocks
(threshold / burn-rate / absence, for_s, dedup, cooldown, resolve,
guards), the Prometheus ``parallax_alerts`` surface, flight-dump
integration, ops_report reconstruction, and the chaos guard
(tools/check_goodput.py) end to end."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import parallax_tpu as parallax
from parallax_tpu import obs
from parallax_tpu.models import simple
from parallax_tpu.obs.alerts import (AlertEngine, AlertRule,
                                     builtin_rules)
from parallax_tpu.obs.export import render_prometheus
from parallax_tpu.obs.goodput import (BADPUT_CLASSES, GoodputLedger,
                                      dominant_badput, step_goodput)
from parallax_tpu.obs.journal import EventJournal, read_journal
from parallax_tpu.obs.metrics import MetricsRegistry


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def _engine(reg, *rules, clock=None, **kw):
    return AlertEngine(reg, rules=tuple(rules),
                       clock=clock or FakeClock(), **kw)


# -- event journal ---------------------------------------------------------


class TestEventJournal:
    def test_seq_is_causal_and_ring_bounded(self):
        j = EventJournal(capacity=4, registry=MetricsRegistry())
        for i in range(10):
            j.emit("t", "tick", i=i)
        assert j.seq == 10
        ring = j.events()
        assert len(ring) == 4  # bounded
        seqs = [e["seq"] for e in ring]
        assert seqs == sorted(seqs) == [7, 8, 9, 10]
        # tail returns oldest-first copies
        tail = j.tail(2)
        assert [e["seq"] for e in tail] == [9, 10]
        tail[0]["seq"] = -1
        assert j.events()[-2]["seq"] == 9  # copy, not alias

    def test_event_envelope_and_correlation_ids(self):
        j = EventJournal(registry=MetricsRegistry())
        e = j.emit("ckpt", "save", severity="warning",
                   incident_id="inc-1", request_id="r9", step=4)
        assert e["subsystem"] == "ckpt" and e["kind"] == "save"
        assert e["severity"] == "warning"
        assert e["incident_id"] == "inc-1"
        assert e["request_id"] == "r9"
        assert e["fields"] == {"step": 4}
        # unknown severities normalize instead of poisoning the stream
        assert j.emit("t", "x", severity="catastrophic")["severity"] \
            == "info"
        # a payload field named `kind` must not collide with the
        # envelope (subsystem/kind are positional-only)
        e2 = j.emit("anomaly", "spike", kind="loss")
        assert e2["kind"] == "spike"
        assert e2["fields"]["kind"] == "loss"

    def test_jsonl_sink_and_rotation(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        j = EventJournal(capacity=8, path=p, max_bytes=600,
                         registry=MetricsRegistry())
        for i in range(20):
            j.emit("t", "tick", i=i, pad="x" * 40)
        assert os.path.exists(p + ".1")  # rotated
        # the live file holds a readable suffix of the stream
        evs = read_journal(p)
        assert evs and evs[-1]["fields"]["i"] == 19
        assert all(e["subsystem"] == "t" for e in evs)

    def test_read_journal_skips_garbage(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        with open(p, "w") as f:
            f.write('{"seq": 2, "ts": 5.0, "kind": "b"}\n')
            f.write("NOT JSON AT ALL\n")
            f.write('{"seq": 1, "ts": 4.0, "kind": "a"}\n')
        evs = read_journal(p)
        assert [e["kind"] for e in evs] == ["a", "b"]  # ts-ordered
        assert read_journal(str(tmp_path / "missing.jsonl")) == []

    def test_killswitch_emit_is_noop(self):
        j = EventJournal(registry=MetricsRegistry())
        j.emit("t", "kept")
        obs.disable()
        try:
            assert j.emit("t", "dropped") is None
        finally:
            obs.enable()
        assert j.seq == 1
        assert [e["kind"] for e in j.events()] == ["kept"]

    def test_non_json_fields_degrade_not_kill(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        j = EventJournal(path=p, registry=MetricsRegistry())
        j.emit("t", "np", value=np.float32(1.5), arr=np.arange(2))
        assert len(read_journal(p)) == 1  # stringified, not lost

    def test_validation(self):
        with pytest.raises(ValueError):
            EventJournal(capacity=0)
        with pytest.raises(ValueError):
            EventJournal(max_bytes=0)


# -- goodput ledger --------------------------------------------------------


def _row(step, wall_ms, data_wait_ms=0.0):
    return {"step": step, "wall_ms": wall_ms,
            "data_wait_ms": data_wait_ms}


class TestGoodputLedger:
    def test_step_partition_and_sum_to_wall(self):
        led = GoodputLedger(MetricsRegistry())
        led.on_step(_row(0, 100.0, data_wait_ms=10.0))
        led.on_step(_row(1, 50.0))
        acct = led.account()
        assert acct["steps"] == 2
        assert acct["productive_s"] == pytest.approx(0.14)
        assert acct["badput_s"]["data_wait"] == pytest.approx(0.01)
        # the invariant: productive + sum(badput incl unattributed)
        # == wall EXACTLY, because unattributed is the remainder
        total = acct["productive_s"] + sum(acct["badput_s"].values())
        assert total == pytest.approx(acct["wall_s"], abs=1e-6)
        assert set(BADPUT_CLASSES) <= set(acct["badput_s"])

    def test_note_badput_carve_moves_not_adds(self):
        led = GoodputLedger(MetricsRegistry())
        led.on_step(_row(0, 1000.0))
        led.note_badput("ckpt_stall", 0.3, carve_from_productive=True)
        acct = led.account()
        assert acct["productive_s"] == pytest.approx(0.7)
        assert acct["badput_s"]["ckpt_stall"] == pytest.approx(0.3)
        with pytest.raises(ValueError):
            led.note_badput("no_such_class", 1.0)

    def test_rollback_refunds_measured_time(self):
        led = GoodputLedger(MetricsRegistry())
        for s in range(6):
            led.on_step(_row(s, 100.0))
        # snapshot step 4 (post-increment numbering): steps 4 and 5
        # are the rewound work
        moved = led.on_rollback(4)
        assert moved == pytest.approx(0.2)
        acct = led.account()
        assert acct["badput_s"]["rollback_discarded"] \
            == pytest.approx(0.2)
        assert acct["productive_s"] == pytest.approx(0.4)
        # a second rollback to the same step moves nothing new
        assert led.on_rollback(4) == 0.0

    def test_run_epoch_anchors_startup_as_compile_warmup(self):
        led = GoodputLedger(MetricsRegistry(),
                            run_epoch=time.time() - 30.0)
        acct = led.account()
        assert acct["badput_s"]["compile_warmup"] \
            == pytest.approx(30.0, abs=2.0)
        assert acct["wall_s"] >= 30.0

    def test_restore_spans_attempts_and_books_the_gap(self):
        led1 = GoodputLedger(MetricsRegistry(),
                             run_epoch=time.time() - 10.0)
        led1.on_step(_row(0, 2000.0))
        snap = led1.snapshot()
        assert snap["attempts"] == 1
        # fake a 5s eviction gap before the next attempt's anchor
        snap["saved_at"] = time.time() - 5.0
        led2 = GoodputLedger(MetricsRegistry(),
                             run_epoch=time.time())
        led2.restore_snapshot(snap, restore_s=0.25, replay_s=0.05)
        acct = led2.account()
        assert acct["attempts"] == 2
        assert acct["steps"] == 1
        assert acct["badput_s"]["eviction_downtime"] \
            == pytest.approx(5.0, abs=1.0)
        assert acct["badput_s"]["restore_replay"] \
            == pytest.approx(0.30)
        # the gap joined the cumulative wall too: wall ~= attempt1's
        # 10s + 5s gap + this attempt's epsilon, and still sums
        assert acct["wall_s"] == pytest.approx(15.0, abs=1.5)
        total = acct["productive_s"] + sum(acct["badput_s"].values())
        assert total == pytest.approx(acct["wall_s"], abs=1e-6)

    def test_killswitch_on_step_is_noop(self):
        led = GoodputLedger(MetricsRegistry())
        obs.disable()
        try:
            led.on_step(_row(0, 100.0))
            led.note_badput("data_wait", 1.0)
            assert led.on_rollback(0) == 0.0
        finally:
            obs.enable()
        acct = led.account()
        assert acct["steps"] == 0
        assert sum(v for k, v in acct["badput_s"].items()
                   if k != "unattributed") == 0.0

    def test_dominant_badput(self):
        assert dominant_badput({"badput_s": {}}) is None
        assert dominant_badput(
            {"badput_s": {"data_wait": 0.0}}) is None
        assert dominant_badput(
            {"badput_s": {"data_wait": 1.0,
                          "ckpt_stall": 3.0}}) == "ckpt_stall"

    def test_timeline_goodput_delegates_to_step_goodput(self):
        tl = obs.StepTimeline(MetricsRegistry(), capacity=16)
        for s in range(4):
            tl.record_step(s, 0.0, 1e-3, 1e-4, 1e-4, 1e-4, 5e-4, 0.0)
        # single owner of the math: the method and the function agree
        # key for key (bench.py's goodput keys keep their meaning)
        assert tl.goodput() == step_goodput(tl)
        assert tl.goodput()["steps"] == 4
        assert "phase_frac" in tl.goodput()


# -- alert engine ----------------------------------------------------------


class TestAlertRule:
    def test_validation(self):
        with pytest.raises(ValueError):
            AlertRule("x", "m", kind="nope")
        with pytest.raises(ValueError):
            AlertRule("x", "m", op="!=")
        with pytest.raises(ValueError):
            AlertRule("x", "m", kind="burn_rate", window_s=0)

    def test_builtin_rules_cover_the_stock_signals(self):
        rules = {r.name: r for r in builtin_rules(goodput_floor=0.4)}
        assert rules["slo_burn"].metric \
            == "serve.slo.deadline_miss_budget_consumed"
        assert rules["instability"].metric == "health.instability"
        assert rules["serve_recompiles"].kind == "burn_rate"
        assert rules["page_pool_exhausted"].metric \
            == "serve.kv_refill_deferred"
        gf = rules["goodput_floor"]
        assert gf.threshold == 0.4 and gf.op == "<"
        assert gf.guard_metric == "ops.wall_s"  # no early-run flap


class TestAlertEngine:
    def test_threshold_lifecycle_pending_firing_resolved(self):
        reg = MetricsRegistry()
        clk = FakeClock()
        eng = _engine(reg, AlertRule("hot", "g", op=">",
                                     threshold=5.0, for_s=10.0,
                                     cooldown_s=0.0), clock=clk)
        g = reg.gauge("g")
        g.set(1.0)
        assert eng.evaluate() == [] and eng.state("hot") == "ok"
        g.set(9.0)
        clk.t = 100.0
        assert eng.evaluate() == []  # breach not yet sustained
        assert eng.state("hot") == "pending"
        clk.t = 111.0
        fired = eng.evaluate()
        assert [e["transition"] for e in fired] == ["firing"]
        assert eng.state("hot") == "firing"
        assert eng.active() == ["hot"]
        # dedup: still breached -> no re-emission
        clk.t = 112.0
        assert eng.evaluate() == []
        g.set(1.0)
        clk.t = 113.0
        assert [e["transition"] for e in eng.evaluate()] \
            == ["resolved"]
        assert eng.state("hot") == "ok" and eng.active() == []

    def test_cooldown_suppresses_the_refire_flap(self):
        reg = MetricsRegistry()
        clk = FakeClock()
        eng = _engine(reg, AlertRule("flap", "g", op=">",
                                     threshold=0.5, for_s=0.0,
                                     cooldown_s=30.0), clock=clk)
        g = reg.gauge("g")
        g.set(1.0)
        clk.t = 1.0
        assert len(eng.evaluate()) == 1  # fires
        g.set(0.0)
        clk.t = 2.0
        assert len(eng.evaluate()) == 1  # resolves
        g.set(1.0)
        clk.t = 3.0
        assert eng.evaluate() == []  # inside cooldown: suppressed
        clk.t = 40.0
        assert [e["transition"] for e in eng.evaluate()] == ["firing"]
        assert eng.summary()["firings_total"] == 2

    def test_burn_rate_fires_on_counter_slope(self):
        reg = MetricsRegistry()
        clk = FakeClock()
        eng = _engine(reg, AlertRule("burn", "c", kind="burn_rate",
                                     op=">", threshold=0.5,
                                     window_s=60.0, cooldown_s=0.0),
                      clock=clk)
        c = reg.counter("c")
        clk.t = 0.0
        assert eng.evaluate() == []  # one sample: no slope yet
        clk.t = 10.0
        assert eng.evaluate() == []  # flat: rate 0
        c.inc(100)
        clk.t = 20.0
        assert [e["transition"] for e in eng.evaluate()] == ["firing"]
        # flat again long enough for the window to forget the spike
        clk.t = 90.0
        assert [e["transition"] for e in eng.evaluate()] \
            == ["resolved"]

    def test_absence_fires_until_the_metric_appears(self):
        reg = MetricsRegistry()
        clk = FakeClock()
        eng = _engine(reg, AlertRule("dead", "heartbeat",
                                     kind="absence", cooldown_s=0.0),
                      clock=clk)
        clk.t = 1.0
        assert [e["transition"] for e in eng.evaluate()] == ["firing"]
        reg.gauge("heartbeat").set(1.0)
        clk.t = 2.0
        assert [e["transition"] for e in eng.evaluate()] \
            == ["resolved"]

    def test_guard_metric_gates_until_signal(self):
        reg = MetricsRegistry()
        clk = FakeClock()
        eng = _engine(reg, AlertRule("floor", "frac", op="<",
                                     threshold=0.5,
                                     guard_metric="wall",
                                     guard_min=100.0,
                                     cooldown_s=0.0), clock=clk)
        reg.gauge("frac").set(0.01)  # would breach
        reg.gauge("wall").set(5.0)   # but the run is too young
        clk.t = 1.0
        assert eng.evaluate() == []
        reg.gauge("wall").set(200.0)
        clk.t = 2.0
        assert [e["transition"] for e in eng.evaluate()] == ["firing"]

    def test_dotted_metric_resolves_into_summary(self):
        reg = MetricsRegistry()
        clk = FakeClock()
        h = reg.histogram("lat_ms")
        for v in (1.0, 2.0, 100.0):
            h.record(v)
        eng = _engine(reg, AlertRule("p", "lat_ms.max", op=">",
                                     threshold=50.0, cooldown_s=0.0),
                      clock=clk)
        clk.t = 1.0
        assert [e["transition"] for e in eng.evaluate()] == ["firing"]

    def test_transitions_land_in_journal_and_flight(self, tmp_path):
        reg = MetricsRegistry()
        clk = FakeClock()
        j = EventJournal(registry=reg)

        class SpyFlight:
            def __init__(self):
                self.triggers = []

            def trigger(self, reason, detail):
                self.triggers.append((reason, detail))

        fl = SpyFlight()
        eng = AlertEngine(reg, rules=(AlertRule(
            "hot", "g", op=">", threshold=0.5, cooldown_s=0.0,
            severity="error"),), journal=j, flight=fl, clock=clk)
        reg.gauge("g").set(1.0)
        clk.t = 1.0
        eng.evaluate()
        ev = [e for e in j.events() if e["subsystem"] == "alert"]
        assert ev and ev[-1]["kind"] == "firing"
        assert ev[-1]["severity"] == "error"
        assert ev[-1]["fields"]["alert"] == "hot"
        assert fl.triggers and fl.triggers[0][0] == "alert:hot"
        reg.gauge("g").set(0.0)
        clk.t = 2.0
        eng.evaluate()
        assert [e["kind"] for e in j.events()
                if e["subsystem"] == "alert"] == ["firing", "resolved"]
        # resolve does NOT re-dump flight
        assert len(fl.triggers) == 1

    def test_prometheus_alert_rows(self):
        reg = MetricsRegistry()
        clk = FakeClock()
        eng = _engine(reg,
                      AlertRule("hot", "g", op=">", threshold=0.5,
                                cooldown_s=0.0, severity="error"),
                      AlertRule("cold", "g", op="<", threshold=-1.0,
                                cooldown_s=0.0), clock=clk)
        reg.gauge("g").set(1.0)
        clk.t = 1.0
        eng.evaluate()
        rows = eng.prometheus_alerts()
        by_name = {r["alert"]: r for r in rows}
        assert by_name["hot"]["state"] == "firing"
        assert by_name["hot"]["value"] == 1.0
        assert by_name["cold"]["value"] == 0.0
        text = render_prometheus({"": reg.snapshot()}, alerts=rows)
        assert 'parallax_alerts{alert="hot",severity="error",' \
               'state="firing"} 1.0' in text
        # the engine's own counters surface too
        assert "parallax_alerts_firings 1.0" in text

    def test_poll_throttles_and_thread_start_stop(self):
        reg = MetricsRegistry()
        clk = FakeClock()
        eng = AlertEngine(reg, rules=(AlertRule(
            "hot", "g", op=">", threshold=0.5, cooldown_s=0.0),),
            interval_s=30.0, clock=clk)
        reg.gauge("g").set(1.0)
        clk.t = 1.0
        eng.poll()  # first poll evaluates
        assert eng.state("hot") == "firing"
        reg.gauge("g").set(0.0)
        clk.t = 10.0
        eng.poll()  # inside the interval: no pass
        assert eng.state("hot") == "firing"
        clk.t = 40.0
        eng.poll()
        assert eng.state("hot") == "ok"
        # daemon thread: starts, evaluates, stops cleanly
        eng2 = AlertEngine(reg, rules=(), interval_s=0.01)
        eng2.start()
        time.sleep(0.05)
        eng2.stop()
        assert int(reg.snapshot()["alerts.evals"]) >= 1

    def test_evaluate_never_raises_on_poisoned_gauge(self):
        reg = MetricsRegistry()

        def boom():
            raise RuntimeError("poisoned")

        reg.gauge("bad").set_fn(boom)
        eng = _engine(reg, AlertRule("x", "bad", op=">",
                                     threshold=0.0))
        assert eng.evaluate() == []  # snapshot failure swallowed

    def test_killswitch_structural_noop(self):
        reg = MetricsRegistry()
        clk = FakeClock()
        eng = _engine(reg, AlertRule("hot", "g", op=">",
                                     threshold=0.5, cooldown_s=0.0),
                      clock=clk)
        reg.gauge("g").set(1.0)
        obs.disable()
        try:
            clk.t = 1.0
            assert eng.evaluate() == []
            eng.poll()
        finally:
            obs.enable()
        assert eng.state("hot") == "ok"

    def test_clean_session_fires_no_builtin_alert(self):
        # the builtin ruleset over a healthy training registry: no
        # serve metrics, low instability, guarded goodput floor
        reg = MetricsRegistry()
        reg.gauge("health.instability").set(0.1)
        reg.gauge("ops.goodput_fraction").set(0.05)  # early-run low
        reg.gauge("ops.wall_s").set(30.0)            # ...but young
        clk = FakeClock()
        eng = AlertEngine(reg, rules=builtin_rules(), clock=clk)
        for t in (1.0, 50.0, 100.0):
            clk.t = t
            assert eng.evaluate() == []
        assert eng.active() == []


# -- session integration ---------------------------------------------------


def _session(**cfg_kw):
    sess, *_ = parallax.parallel_run(
        simple.build_model(learning_rate=0.1),
        parallax_config=parallax.Config(run_option="AR",
                                        search_partitions=False,
                                        **cfg_kw))
    return sess


class TestSessionIntegration:
    def test_ledger_persists_across_ckpt_save_restore(self, tmp_path):
        ck = str(tmp_path / "ck")
        rng = np.random.default_rng(0)
        sess = _session(ckpt_config=parallax.CheckPointConfig(
            ckpt_dir=ck, save_ckpt_steps=2))
        for i in range(4):
            sess.run(feed_dict=simple.make_batch(rng, 32))
        acct1 = sess.ops_account()
        assert acct1["attempts"] == 1 and acct1["steps"] == 4
        sess.close()
        # a second session on the same ckpt_dir restores the manifest
        # extras: the ledger continues the account as attempt 2
        sess2 = _session(ckpt_config=parallax.CheckPointConfig(
            ckpt_dir=ck, save_ckpt_steps=2))
        sess2.prepare(simple.make_batch(rng, 32))
        try:
            acct2 = sess2.ops_account()
            assert acct2["attempts"] == 2
            assert acct2["steps"] >= 4  # attempt 1's steps adopted
            assert acct2["badput_s"]["restore_replay"] > 0
            total = acct2["productive_s"] \
                + sum(acct2["badput_s"].values())
            assert total == pytest.approx(acct2["wall_s"], abs=1e-4)
        finally:
            sess2.close()

    def test_flight_dump_embeds_journal_ops_alerts(self, tmp_path):
        sess = _session(journal_path=str(tmp_path / "j.jsonl"))
        rng = np.random.default_rng(0)
        try:
            sess.run(feed_dict=simple.make_batch(rng, 32))
            sess.journal.emit("test", "marker", note="breadcrumb")
            path = sess.dump_flight(path=str(tmp_path / "f.json"))
            with open(path) as f:
                doc = json.load(f)
            tail = doc["journal_tail"]
            assert any(e["kind"] == "marker" for e in tail)
            assert doc["ops"]["wall_s"] > 0
            assert "goodput_fraction" in doc["ops"]
            assert doc["alerts"]["rules"] >= 5  # builtins armed
            assert doc["alerts"]["firing"] == []
            # the dump itself journaled, carrying its incident id
            ev = [e for e in sess.journal.events()
                  if e["subsystem"] == "flight"]
            assert ev and ev[-1]["incident_id"] == doc["incident_id"]
        finally:
            sess.close()

    def test_session_close_journals_and_stops_alerts(self, tmp_path):
        jp = str(tmp_path / "j.jsonl")
        sess = _session(journal_path=jp)
        rng = np.random.default_rng(0)
        sess.run(feed_dict=simple.make_batch(rng, 32))
        sess.close()
        evs = read_journal(jp)
        assert [e for e in evs if (e["subsystem"], e["kind"])
                == ("session", "close")]

    def test_ckpt_saves_journal(self, tmp_path):
        sess = _session(
            journal_path=str(tmp_path / "j.jsonl"),
            ckpt_config=parallax.CheckPointConfig(
                ckpt_dir=str(tmp_path / "ck"), save_ckpt_steps=2))
        rng = np.random.default_rng(0)
        try:
            for i in range(4):
                sess.run(feed_dict=simple.make_batch(rng, 32))
            kinds = [(e["subsystem"], e["kind"])
                     for e in sess.journal.events()]
            assert kinds.count(("ckpt", "save")) == 2
        finally:
            sess.close()

    def test_config_validation(self):
        with pytest.raises(ValueError):
            parallax.Config(journal_capacity=0)
        with pytest.raises(ValueError):
            parallax.Config(journal_max_bytes=-1)
        with pytest.raises(ValueError):
            parallax.Config(alert_interval_s=0)
        with pytest.raises(ValueError):
            parallax.Config(goodput_floor=1.5)


# -- ops_report ------------------------------------------------------------


class TestOpsReport:
    def test_build_report_and_render(self, tmp_path):
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        from tools.ops_report import build_report, render_text
        events = [
            {"seq": 1, "ts": 1.0, "subsystem": "ckpt",
             "kind": "save", "severity": "info"},
            {"seq": 2, "ts": 2.0, "subsystem": "alert",
             "kind": "firing", "severity": "error",
             "fields": {"alert": "hot"}},
            {"seq": 3, "ts": 3.0, "subsystem": "flight",
             "kind": "dump", "severity": "warning",
             "incident_id": "inc-7"},
            # a resumed attempt: seq restarts at 1
            {"seq": 1, "ts": 10.0, "subsystem": "ckpt",
             "kind": "restored", "severity": "info"},
        ]
        account = {"wall_s": 100.0, "goodput_fraction": 0.7,
                   "steps": 10, "attempts": 2,
                   "badput_s": {"ckpt_stall": 2.0,
                                "eviction_downtime": 20.0}}
        rep = build_report(events, account)
        assert rep["events"] == 4
        assert rep["attempts_in_journal"] == 2
        assert rep["incident_ids"] == ["inc-7"]
        assert rep["unresolved_alerts"] == ["hot"]
        assert rep["dominant_badput"] == "eviction_downtime"
        text = render_text(events, account, rep)
        assert "eviction_downtime" in text and "dominant" in text
        assert "STILL FIRING: hot" in text


# -- the chaos guard (tier-1 gate) -----------------------------------------


def test_goodput_chaos_guard():
    """tools/check_goodput.py end to end: clean run sums to the
    parent-measured wall within 5% and fires zero alerts; SIGKILL +
    resume yields one cumulative ledger spanning both attempts with
    restore_replay and eviction_downtime attributed; a NaN rollback
    books the discarded steps' measured time in its own class with
    the journal events in causal order."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.setdefault("XLA_FLAGS",
                   "--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "check_goodput.py")],
        env=env, capture_output=True, text=True, timeout=560,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, (proc.stdout[-3000:]
                                  + proc.stderr[-2000:])
    result = json.loads(proc.stdout)
    assert result["ok"], result["violations"]
    assert result["clean"]["alerts_fired"] == 0
    assert result["clean"]["wall_rel_err"] <= 0.05
    assert result["sigkill"]["attempts"] == 2
    assert result["sigkill"]["wall_rel_err"] <= 0.05
    assert result["nan"]["rollback_discarded_s"] > 0
