"""NMT data utilities + KV-cached decoding (VERDICT r3 item 6).

Reference parity: examples/nmt/utils/vocab_utils.py + iterator_utils.py
and nmt_test.py:48-79 (testInference-style train->decode->BLEU golden).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import parallax_tpu as parallax
from parallax_tpu.common.evaluation import corpus_bleu
from parallax_tpu.data import nmt_data
from parallax_tpu.models import nmt

DATA = os.path.join(os.path.dirname(__file__), "data", "nmt")


def test_vocab_specials_and_unk_roundtrip():
    v = nmt_data.Vocab.load(os.path.join(DATA, "vocab.txt"))
    assert v.id_to_token[:4] == ["<pad>", "<s>", "</s>", "<unk>"]
    assert v.token_to_id["<pad>"] == nmt_data.PAD_ID
    ids = v.encode("a b zzz j")
    assert ids[2] == nmt_data.UNK_ID
    assert v.decode(ids + [nmt_data.EOS_ID, 9]) == ["a", "b", "<unk>", "j"]

    # a vocab file without specials gets them prepended (check_vocab)
    v2 = nmt_data.Vocab(["x", "y"])
    assert v2.id_to_token[:4] == ["<pad>", "<s>", "</s>", "<unk>"]
    assert v2.token_to_id["x"] == 4


def test_corpus_loading_and_length_filter(tmp_path):
    v = nmt_data.Vocab.load(os.path.join(DATA, "vocab.txt"))
    pairs = nmt_data.load_parallel_corpus(
        os.path.join(DATA, "train.src"), os.path.join(DATA, "train.tgt"),
        v, max_len=16)
    assert len(pairs) == 96
    for s, t in pairs:
        assert s == t                      # checked-in corpus: copy task
        assert 3 <= len(s) <= 8
    # the length filter drops long pairs
    short = nmt_data.load_parallel_corpus(
        os.path.join(DATA, "train.src"), os.path.join(DATA, "train.tgt"),
        v, max_len=4)
    assert 0 < len(short) < 96
    assert all(len(s) <= 4 for s, _ in short)


def test_iterator_static_buckets_and_feed_contract():
    v = nmt_data.Vocab.load(os.path.join(DATA, "vocab.txt"))
    pairs = nmt_data.load_parallel_corpus(
        os.path.join(DATA, "train.src"), os.path.join(DATA, "train.tgt"),
        v, max_len=16)
    it = nmt_data.NMTBatchIterator(pairs, batch_size=8, max_len=16,
                                   bucket_width=8)
    shapes = set()
    n = 0
    for batch in it.epoch(0):
        assert set(batch) == {"src", "tgt_in", "tgt_out", "w"}
        B, T = batch["src"].shape
        assert B == 8 and T % 8 == 0 and T <= 16
        shapes.add(batch["src"].shape)
        # BOS-prefixed input, EOS-suffixed output, weights cover tgt+EOS
        assert (batch["tgt_in"][:, 0] == nmt_data.BOS_ID).all()
        lens = (batch["w"] > 0).sum(axis=1)
        for r in range(B):
            L = int(lens[r]) - 1  # minus the EOS slot
            assert batch["tgt_out"][r, L] == nmt_data.EOS_ID
            np.testing.assert_array_equal(
                batch["tgt_in"][r, 1:L + 1], batch["tgt_out"][r, :L])
        n += 1
    assert n >= 2
    # static shapes: only a handful of bucket-bound shapes ever compiled
    assert len(shapes) <= 2, shapes


def test_iterator_sharding_partitions_the_corpus():
    v = nmt_data.Vocab.load(os.path.join(DATA, "vocab.txt"))
    pairs = nmt_data.load_parallel_corpus(
        os.path.join(DATA, "train.src"), os.path.join(DATA, "train.tgt"),
        v, max_len=16)

    def shard_batches(shard_index):
        it = nmt_data.NMTBatchIterator(
            pairs, batch_size=4, max_len=16, num_shards=2,
            shard_index=shard_index, drop_remainder=False)
        return list(it.epoch(0))

    b0, b1 = shard_batches(0), shard_batches(1)
    # SPMD lockstep: same number of steps, same shapes at every step
    assert len(b0) == len(b1) >= 1
    for a, b in zip(b0, b1):
        assert a["src"].shape == b["src"].shape
        assert a["src"].shape[0] == 2  # batch_size / num_shards rows

    def real_rows(batches):
        return sum(int(b["w"][r].sum() > 0)
                   for b in batches for r in range(b["src"].shape[0]))

    # the row stripes partition the corpus exactly
    assert real_rows(b0) + real_rows(b1) == len(pairs)


def test_cached_decode_matches_cacheless(rng):
    cfg = nmt.tiny_config(compute_dtype=jnp.float32)
    params = nmt.build_model(cfg).init_fn(jax.random.PRNGKey(0))
    src = rng.integers(4, cfg.vocab_size, (4, 8)).astype(np.int32)

    g_cached = np.asarray(nmt.greedy_decode(params, cfg, src, max_len=12))
    g_plain = np.asarray(nmt.greedy_decode(params, cfg, src, max_len=12,
                                           use_cache=False))
    np.testing.assert_array_equal(g_cached, g_plain)

    b_cached = np.asarray(nmt.beam_decode(params, cfg, src, beam_width=3,
                                          max_len=12))
    b_plain = np.asarray(nmt.beam_decode(params, cfg, src, beam_width=3,
                                         max_len=12, use_cache=False))
    np.testing.assert_array_equal(b_cached, b_plain)


@pytest.mark.slow
def test_file_corpus_train_decode_bleu_golden():
    """Reference nmt_test.py:48-79 analogue: train on the checked-in
    file corpus through parallel_run, KV-cached greedy decode, corpus
    BLEU above the golden bar."""
    v = nmt_data.Vocab.load(os.path.join(DATA, "vocab.txt"))
    pairs = nmt_data.load_parallel_corpus(
        os.path.join(DATA, "train.src"), os.path.join(DATA, "train.tgt"),
        v, max_len=16)
    cfg = nmt.tiny_config(vocab_size=len(v), max_len=16,
                          learning_rate=3e-3, warmup_steps=20,
                          compute_dtype=jnp.float32)
    sess, *_ = parallax.parallel_run(
        nmt.build_model(cfg),
        parallax_config=parallax.Config(run_option="HYBRID",
                                        search_partitions=False))
    it = nmt_data.NMTBatchIterator(pairs, batch_size=16, max_len=16,
                                   bucket_width=16)
    loss = None
    for epoch in range(40):
        for batch in it.epoch(epoch):
            loss = sess.run("loss", feed_dict=batch)
    params = sess.state.params
    sess.close()
    assert float(loss) < 1.0, f"copy task failed to train: loss={loss}"

    hyps, refs = [], []
    eval_pairs = pairs[:32]
    src = np.full((len(eval_pairs), 16), nmt_data.PAD_ID, np.int32)
    for i, (s, _) in enumerate(eval_pairs):
        src[i, :len(s)] = s
    out = np.asarray(nmt.greedy_decode(params, cfg, src, max_len=12))
    for row, (s, t) in zip(out, eval_pairs):
        hyps.append(nmt.ids_to_tokens(row))
        refs.append([str(i) for i in t])
    bleu = corpus_bleu(refs, hyps)
    assert bleu >= 40.0, f"BLEU {bleu:.1f} below golden 40.0"
