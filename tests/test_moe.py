"""Expert-parallel MoE tests: sharded dispatch/combine matches the
unsharded reference path; gradients flow; capacity drops are bounded
and ACCOUNTED (never silent); top-2 (GShard) routing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parallax_tpu.core import mesh as mesh_lib
from parallax_tpu.ops import moe


B, D, F, E = 64, 16, 32, 8


@pytest.fixture
def weights(rng):
    return (
        jnp.asarray(rng.standard_normal((D, E)).astype(np.float32)) * 0.5,
        jnp.asarray(rng.standard_normal((E, D, F)).astype(np.float32))
        * 0.1,
        jnp.asarray(rng.standard_normal((E, F, D)).astype(np.float32))
        * 0.1,
    )


@pytest.fixture
def tokens(rng):
    return jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))


@pytest.mark.parametrize("p", [2, 4, 8])
@pytest.mark.parametrize("k", [1, 2])
def test_sharded_matches_dense_path(tokens, weights, p, k):
    router, w1, w2 = weights
    mesh = mesh_lib.build_mesh(num_partitions=p)
    # generous capacity so nothing is dropped -> exact match
    ref, aux_ref, drop_ref = moe.switch_moe(
        tokens, router, w1, w2, None, capacity_factor=float(E), top_k=k)
    got, aux, dropped = moe.switch_moe(
        tokens, router, w1, w2, mesh, capacity_factor=float(E), top_k=k)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-6)
    assert float(dropped) == 0.0 and float(drop_ref) == 0.0


@pytest.mark.parametrize("k", [1, 2])
def test_gradients_flow_through_dispatch(tokens, weights, k):
    router, w1, w2 = weights
    mesh = mesh_lib.build_mesh(num_partitions=4)

    def loss(w1, w2, tokens):
        out, aux, _ = moe.switch_moe(tokens, router, w1, w2, mesh,
                                     capacity_factor=float(E), top_k=k)
        return jnp.sum(out ** 2) + 0.01 * aux

    g1, g2 = jax.jit(jax.grad(loss, argnums=(0, 1)))(w1, w2, tokens)

    def ref_loss(w1, w2, tokens):
        out, aux, _ = moe.switch_moe(tokens, router, w1, w2, None,
                                     capacity_factor=float(E), top_k=k)
        return jnp.sum(out ** 2) + 0.01 * aux

    e1, e2 = jax.grad(ref_loss, argnums=(0, 1))(w1, w2, tokens)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(e1), rtol=1e-4,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(e2), rtol=1e-4,
                               atol=1e-6)


def test_capacity_drops_are_accounted(tokens, weights):
    """With tight capacity some tokens drop (zero output) — and the
    dropped fraction REPORTS it (silent drops were VERDICT weak #8)."""
    router, w1, w2 = weights
    mesh = mesh_lib.build_mesh(num_partitions=4)
    out, aux, dropped = moe.switch_moe(tokens, router, w1, w2, mesh,
                                       capacity_factor=0.5)
    assert out.shape == (B, D)
    assert np.isfinite(np.asarray(out)).all()
    # at least one token dropped given the skewed router
    zero_rows = np.asarray((jnp.sum(jnp.abs(out), axis=1) == 0))
    assert zero_rows.any()
    assert float(dropped) > 0.0
    # the accounting matches the observable zero rows at k=1: a dropped
    # (token, choice) IS a zeroed token output
    np.testing.assert_allclose(float(dropped), zero_rows.mean(),
                               atol=0.02)


def test_top2_gates_renormalized(weights):
    """Top-2 output = g1*f(e1) + g2*f(e2) with g1+g2 = 1."""
    rng = np.random.default_rng(7)
    router, w1, w2 = weights
    toks = jnp.asarray(rng.standard_normal((4, D)).astype(np.float32))
    out, _, _ = moe.switch_moe(toks, router, w1, w2, None, top_k=2)
    probs = jax.nn.softmax(toks @ router, axis=-1)
    tp, ti = jax.lax.top_k(probs, 2)
    g = tp / tp.sum(-1, keepdims=True)

    def f(e, x):
        return jax.nn.relu(x @ w1[e]) @ w2[e]
    expect = np.stack([
        np.asarray(g[i, 0] * f(int(ti[i, 0]), toks[i])
                   + g[i, 1] * f(int(ti[i, 1]), toks[i]))
        for i in range(4)])
    np.testing.assert_allclose(np.asarray(out), expect, rtol=2e-5,
                               atol=2e-6)


def test_first_choice_has_capacity_priority(weights):
    """When capacity is scarce, first choices must win slots over
    second choices (GShard priority)."""
    rng = np.random.default_rng(3)
    router, w1, w2 = weights
    toks = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))
    mesh = mesh_lib.build_mesh(num_partitions=4)
    # same tokens, k=1 vs k=2 at the k-scaled same capacity: every slot a
    # first choice occupies at k=1 must still be served at k=2
    out1, _, drop1 = moe.switch_moe(toks, router, w1, w2, mesh,
                                    capacity_factor=1.0, top_k=1)
    out2, _, drop2 = moe.switch_moe(toks, router, w1, w2, mesh,
                                    capacity_factor=1.0, top_k=2)
    served1 = np.asarray(jnp.sum(jnp.abs(out1), axis=1) > 0)
    served2 = np.asarray(jnp.sum(jnp.abs(out2), axis=1) > 0)
    # a token served at k=1 keeps (at least) its first-choice service
    assert (served2 >= served1).all()


def test_aux_loss_uniform_router_is_one():
    """With a uniform router, E * sum f_e p_e == 1 (balanced)."""
    tokens = jnp.ones((32, D))
    router = jnp.zeros((D, E))
    w1 = jnp.zeros((E, D, F))
    w2 = jnp.zeros((E, F, D))
    _, aux, _ = moe.switch_moe(tokens, router, w1, w2, None)
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)


def test_bad_top_k_rejected(tokens, weights):
    router, w1, w2 = weights
    with pytest.raises(ValueError, match="top_k"):
        moe.switch_moe(tokens, router, w1, w2, None, top_k=0)
    with pytest.raises(ValueError, match="top_k"):
        moe.switch_moe(tokens, router, w1, w2, None, top_k=E + 1)
