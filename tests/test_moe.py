"""Expert-parallel MoE tests: sharded dispatch/combine matches the
unsharded reference path; gradients flow; capacity drops are bounded."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parallax_tpu.core import mesh as mesh_lib
from parallax_tpu.ops import moe


B, D, F, E = 64, 16, 32, 8


@pytest.fixture
def weights(rng):
    return (
        jnp.asarray(rng.standard_normal((D, E)).astype(np.float32)) * 0.5,
        jnp.asarray(rng.standard_normal((E, D, F)).astype(np.float32))
        * 0.1,
        jnp.asarray(rng.standard_normal((E, F, D)).astype(np.float32))
        * 0.1,
    )


@pytest.fixture
def tokens(rng):
    return jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))


@pytest.mark.parametrize("p", [2, 4, 8])
def test_sharded_matches_dense_path(tokens, weights, p):
    router, w1, w2 = weights
    mesh = mesh_lib.build_mesh(num_partitions=p)
    # generous capacity so nothing is dropped -> exact match
    ref, aux_ref = moe.switch_moe(tokens, router, w1, w2, None,
                                  capacity_factor=float(E))
    got, aux = moe.switch_moe(tokens, router, w1, w2, mesh,
                              capacity_factor=float(E))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-6)


def test_gradients_flow_through_dispatch(tokens, weights):
    router, w1, w2 = weights
    mesh = mesh_lib.build_mesh(num_partitions=4)

    def loss(w1, w2, tokens):
        out, aux = moe.switch_moe(tokens, router, w1, w2, mesh,
                                  capacity_factor=float(E))
        return jnp.sum(out ** 2) + 0.01 * aux

    g1, g2 = jax.jit(jax.grad(loss, argnums=(0, 1)))(w1, w2, tokens)

    def ref_loss(w1, w2, tokens):
        out, aux = moe.switch_moe(tokens, router, w1, w2, None,
                                  capacity_factor=float(E))
        return jnp.sum(out ** 2) + 0.01 * aux

    e1, e2 = jax.grad(ref_loss, argnums=(0, 1))(w1, w2, tokens)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(e1), rtol=1e-4,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(e2), rtol=1e-4,
                               atol=1e-6)


def test_capacity_bounds_dropped_tokens(tokens, weights):
    """With tight capacity some tokens drop (zero output) but the op
    stays finite and shaped."""
    router, w1, w2 = weights
    mesh = mesh_lib.build_mesh(num_partitions=4)
    out, aux = moe.switch_moe(tokens, router, w1, w2, mesh,
                              capacity_factor=0.5)
    assert out.shape == (B, D)
    assert np.isfinite(np.asarray(out)).all()
    # at least one token dropped given the skewed router
    dropped = np.asarray((jnp.sum(jnp.abs(out), axis=1) == 0))
    assert dropped.any()


def test_aux_loss_uniform_router_is_one():
    """With a uniform router, E * sum f_e p_e == 1 (balanced)."""
    tokens = jnp.ones((32, D))
    router = jnp.zeros((D, E))
    w1 = jnp.zeros((E, D, F))
    w2 = jnp.zeros((E, F, D))
    _, aux = moe.switch_moe(tokens, router, w1, w2, None)
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)
