"""DecodeProgram conformance rig (ISSUE 19): every registered adapter
passes one battery.

The serving subsystem is model-agnostic through the DecodeProgram
contract; this file is the contract's enforcement. It parametrizes
over ``registered_adapters()`` so a new adapter gets the full battery
from its ``register_adapter`` call with zero new test code:

  * paged-vs-dense bit-identity — the paged KV layout is an exact
    re-layout, not an approximation;
  * chunked-prefill identity — layer-chunked prefill composes to the
    same prefix state as one-shot prefill;
  * exact-under-greedy through the scheduler — tokens served through
    ServeSession (slot scatter, continuous refill) match
    ``standalone_greedy`` bit-for-bit, with zero serve-time recompiles
    against the warmed signature set;
  * retire/refill page hygiene — more requests than slots forces
    mid-flight refill, and after drain the pool reports zero pages in
    use (no leak across the retire -> refill boundary).

Fixture builds are the expensive part (each one jits prefill + step),
so they are shared per (adapter, layout) via an lru_cache; tests never
mutate params.
"""

import functools

import numpy as np
import pytest

import parallax_tpu as parallax
from parallax_tpu import ServeConfig
from parallax_tpu.serve import (ServeSession, registered_adapters,
                                standalone_greedy)

ADAPTERS = registered_adapters()
NAMES = sorted(ADAPTERS)
PAGED_NAMES = sorted(n for n in NAMES if ADAPTERS[n].paged)
CHUNKED_NAMES = sorted(n for n in NAMES if ADAPTERS[n].chunked)


@functools.lru_cache(maxsize=None)
def _build(name: str, paged: bool, chunked: bool):
    spec = ADAPTERS[name]
    return spec.build(paged=paged, chunked=chunked)


def _feeds(name: str, n: int, seed: int = 7):
    rng = np.random.default_rng(seed)
    return [ADAPTERS[name].make_feed(rng) for _ in range(n)]


def _serve_config(spec, max_batch: int = 3):
    return parallax.Config(serve_config=ServeConfig(
        max_batch=max_batch, max_queue=64, prefix_cache=spec.paged))


# -- layout identities (device math only, no scheduler) --------------------


@pytest.mark.parametrize("name", PAGED_NAMES)
def test_paged_vs_dense_bit_identity(name):
    spec = ADAPTERS[name]
    prog_p, params_p = _build(name, True, False)
    prog_d, params_d = _build(name, False, False)
    for feed in _feeds(name, 3):
        got_p = standalone_greedy(prog_p, params_p, feed,
                                  max_new_tokens=6)
        got_d = standalone_greedy(prog_d, params_d, feed,
                                  max_new_tokens=6)
        assert got_p == got_d, (name, got_p, got_d)


@pytest.mark.parametrize("name", CHUNKED_NAMES)
def test_chunked_prefill_bit_identity(name):
    prog_c, params_c = _build(name, True, True)
    prog_1, params_1 = _build(name, True, False)
    assert prog_c.num_prefill_chunks > 1
    for feed in _feeds(name, 3):
        got_c = standalone_greedy(prog_c, params_c, feed,
                                  max_new_tokens=6)
        got_1 = standalone_greedy(prog_1, params_1, feed,
                                  max_new_tokens=6)
        assert got_c == got_1, (name, got_c, got_1)


# -- exact-under-greedy through the scheduler ------------------------------


@pytest.mark.parametrize("name", NAMES)
def test_served_tokens_match_standalone_greedy(name):
    """5 requests through a 3-slot session: forces retire + refill, and
    every emitted stream must equal the standalone reference. Zero
    recompiles (the rig reuses the warmed program instance — standalone
    S=1 traces are separate jit entries, not serve-time compiles) and
    zero pages still mapped after drain."""
    spec = ADAPTERS[name]
    prog, params = _build(name, spec.paged, False)
    feeds = _feeds(name, 5, seed=11)
    want = [standalone_greedy(prog, params, f, max_new_tokens=6)
            for f in feeds]
    sess = ServeSession(program=prog, params=params,
                        config=_serve_config(spec))
    try:
        reqs = [sess.submit(f, max_new_tokens=6) for f in feeds]
        got = [[int(t) for t in r.result(timeout=120)] for r in reqs]
    finally:
        sess.close()
    assert got == want, (name, got, want)
    snap = sess.metrics.snapshot()
    assert snap["serve.recompiles"] == 0, (name, snap["serve.recompiles"])
    if spec.paged:
        assert snap["serve.kv_pages_in_use"] == 0, (
            name, snap["serve.kv_pages_in_use"])


@pytest.mark.parametrize("name", PAGED_NAMES)
def test_prefix_replay_continuation_bit_identity(name):
    """Same feed twice with a longer cap the second time: the second
    request must take a prefix hit, replay the cached tokens, then
    CONTINUE past them into fresh pages — and still match the
    standalone stream bit-for-bit (positions-aware page sharing)."""
    spec = ADAPTERS[name]
    prog, params = _build(name, True, False)
    feed = _feeds(name, 1, seed=13)[0]
    want = standalone_greedy(prog, params, feed, max_new_tokens=6)
    sess = ServeSession(program=prog, params=params,
                        config=_serve_config(spec, max_batch=2))
    try:
        t1 = [int(t) for t in
              sess.submit(feed, max_new_tokens=4).result(timeout=120)]
        t2 = [int(t) for t in
              sess.submit(feed, max_new_tokens=6).result(timeout=120)]
    finally:
        sess.close()
    assert t1 == want[:len(t1)], (name, t1, want)
    assert t2 == want, (name, t2, want)
    snap = sess.metrics.snapshot()
    assert snap["serve.prefix.hits"] >= 1
    assert snap["serve.recompiles"] == 0
    assert snap["serve.kv_pages_in_use"] == 0


@pytest.mark.parametrize("name", PAGED_NAMES)
def test_import_prefix_then_decode_bit_identity(name):
    """The disaggregation building block at session scope: prefill_only
    on one session, import the request state into ANOTHER session's
    prefix cache (page-less entry, positions=0), then submit the same
    feed there — the hit admits with zero replayed tokens, insert
    re-scatters the prompt KV into fresh pages, and the stream matches
    standalone exactly."""
    spec = ADAPTERS[name]
    prog, params = _build(name, True, False)
    feed = _feeds(name, 1, seed=17)[0]
    want = standalone_greedy(prog, params, feed, max_new_tokens=6)
    cfg = _serve_config(spec, max_batch=2)
    pre = ServeSession(program=prog, params=params, config=cfg)
    dec = ServeSession(program=prog, params=params, config=cfg)
    try:
        prepared, key, rs = pre.prefill_only(feed)
        assert dec.import_prefix_entry(None, key, rs, positions=0)
        toks = [int(t) for t in
                dec.submit(feed, max_new_tokens=6).result(timeout=120)]
    finally:
        pre.close()
        dec.close()
    assert toks == want, (name, toks, want)
    snap = dec.metrics.snapshot()
    assert snap["serve.prefix.hits"] == 1
    assert snap["serve.prefix.replayed_tokens"] == 0
    assert snap["serve.recompiles"] == 0
    assert snap["serve.kv_pages_in_use"] == 0
