"""BERT pretraining (MLM+NSP) through the hybrid engine."""

import numpy as np
import pytest

import parallax_tpu as parallax
from parallax_tpu.models import bert


@pytest.mark.slow
def test_classification_and_training(rng):
    cfg = bert.tiny_config(num_partitions=8, learning_rate=1e-3)
    model = bert.build_model(cfg)
    sess, *_ = parallax.parallel_run(
        model, parallax_config=parallax.Config(run_option="HYBRID",
                                               search_partitions=False))
    batches = [bert.make_batch(rng, 16, 16, 4, cfg.vocab_size)
               for _ in range(2)]
    out = sess.run(None, feed_dict=batches[0])
    specs = sess.engine.plan.var_specs
    assert specs["word_emb"].is_sparse
    assert not specs["type_emb"].is_sparse     # user override
    assert not specs["mlm/out"].is_sparse      # dense MLM head
    assert not sess.state.params["word_emb"].sharding.is_fully_replicated
    assert out["masked_tokens"] == 16 * 4

    first = out["loss"]
    for i in range(40):
        last = sess.run("loss", feed_dict=batches[i % 2])
    assert last < first * 0.9, (first, last)
    assert np.isfinite(last)
    sess.close()


@pytest.mark.slow
def test_pallas_attention_matches_xla_path(rng):
    """BERT with the Pallas flash kernel (padding mask included) tracks
    the XLA attention trajectory."""
    batches = [bert.make_batch(rng, 16, 16, 4, 500) for _ in range(3)]
    # pad some tokens so the mask actually matters
    for b in batches:
        b["input_ids"][:, -3:] = 0

    def run(use_pallas):
        cfg = bert.tiny_config(num_partitions=8, learning_rate=1e-3,
                               use_pallas_attention=use_pallas)
        sess, *_ = parallax.parallel_run(
            bert.build_model(cfg),
            parallax_config=parallax.Config(run_option="HYBRID",
                                            search_partitions=False))
        losses = [sess.run("loss", feed_dict=b) for b in batches]
        sess.close()
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=2e-3)
