"""Two-process zig-zag ring-attention driver used by test_multihost.py.

Each worker feeds the natural-order process-local slice of one shared
global batch (same seed everywhere); the zig-zag placement happens
in-graph (models/long_context.py), so the 2-process trajectory must match
a single-host run on the same global batch exactly.
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""

import numpy as np  # noqa: E402

import parallax_tpu as parallax  # noqa: E402
from parallax_tpu.models import long_context as lc  # noqa: E402

STEPS, B, T = 5, 2, 32


def main():
    out_path = sys.argv[1]
    cfg = lc.tiny_config(max_len=T)
    cfg.zigzag = True
    model = lc.build_model(cfg)
    sess, num_workers, worker_id, _ = parallax.parallel_run(
        model, resource_info="localhost\n127.0.0.1",
        parallax_config=parallax.Config(run_option="HYBRID",
                                        search_partitions=False),
        num_partitions=8)
    assert num_workers == 2
    losses = []
    for step in range(STEPS):
        batch = lc.make_batch(np.random.default_rng(step), B, T,
                              cfg.vocab_size)
        # natural-order ids; this worker feeds its half of the sequence
        half = T // num_workers
        local = batch["ids"][:, worker_id * half:(worker_id + 1) * half]
        loss = sess.run("loss", feed_dict={"ids": local})
        losses.append(float(loss))
    with open(f"{out_path}.worker{worker_id}", "w") as f:
        f.write(" ".join(f"{x:.6f}" for x in losses) + "\n")
    sess.close()


if __name__ == "__main__":
    main()
