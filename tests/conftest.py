"""Test fixtures: emulate an 8-device TPU mesh on CPU.

SURVEY.md §4: the reference has zero framework tests (everything assumed a
real ssh cluster). Our strategy replaces that with in-process multi-device
tests on a virtual CPU mesh — env vars must be set before jax initializes.
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8"
                           ).strip()

import jax  # noqa: E402

# The environment's sitecustomize may import jax and latch JAX_PLATFORMS
# (e.g. to a real TPU backend) before this conftest runs, so override at
# runtime rather than via env.
jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _devices():
    assert jax.device_count() == 8, (
        f"expected 8 virtual CPU devices, got {jax.device_count()}")
    yield


@pytest.fixture
def rng():
    return np.random.default_rng(42)
