"""Test fixtures: emulate an 8-device TPU mesh on CPU.

SURVEY.md §4: the reference has zero framework tests (everything assumed a
real ssh cluster). Our strategy replaces that with in-process multi-device
tests on a virtual CPU mesh — env vars must be set before jax initializes.
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8"
                           ).strip()

import jax  # noqa: E402

# The environment's sitecustomize may import jax and latch JAX_PLATFORMS
# (e.g. to a real TPU backend) before this conftest runs, so override at
# runtime rather than via env.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite is compile-dominated (every
# engine test pjits a training step), so repeat local runs get most of
# their wall time back. Keyed by HLO + compile env, so a stale cache can
# only miss, never corrupt. Disable with PARALLAX_JIT_CACHE=0.
if os.environ.get("PARALLAX_JIT_CACHE", "1") != "0":
    _cache_dir = os.environ.get(
        "PARALLAX_JIT_CACHE_DIR",
        os.path.abspath(os.path.join(os.path.dirname(__file__), "..",
                                     ".jax_cache")))
    try:
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
        # export to os.environ so SUBPROCESS drivers (test_multihost.py
        # spawns 2-4 jax processes per test via dict(os.environ)) share
        # the cache too — without this every multihost test recompiled
        # every engine in every worker on every run (r5, suite-time
        # item: the drivers were the dominant cold cost)
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)
        os.environ.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    except Exception:  # older jax without the knobs: run uncached
        pass
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _devices():
    assert jax.device_count() == 8, (
        f"expected 8 virtual CPU devices, got {jax.device_count()}")
    yield


@pytest.fixture
def rng():
    return np.random.default_rng(42)
