"""Ring attention (sequence parallelism) numerics tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from parallax_tpu.ops import ring_attention as ra


B, T, H, D = 2, 32, 2, 8


@pytest.fixture
def qkv(rng):
    def t():
        return jnp.asarray(
            rng.standard_normal((B, T, H, D)).astype(np.float32))
    return t(), t(), t()


def _seq_mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("seq",))


@pytest.mark.parametrize("n,causal", [(2, False), (4, False), (8, False),
                                      (4, True), (8, True)])
def test_matches_full_attention(qkv, n, causal):
    q, k, v = qkv
    mesh = _seq_mesh(n)
    expected = ra.full_attention_reference(q, k, v, causal=causal)
    got = jax.jit(lambda q, k, v: ra.ring_attention(
        q, k, v, mesh, "seq", causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-6)


def test_gradients_match_full_attention(qkv):
    q, k, v = qkv
    mesh = _seq_mesh(4)
    g_out = jnp.asarray(np.random.default_rng(3).standard_normal(
        (B, T, H, D)).astype(np.float32))

    def ring_loss(q, k, v):
        return jnp.sum(ra.ring_attention(q, k, v, mesh, "seq",
                                         causal=True) * g_out)

    def full_loss(q, k, v):
        return jnp.sum(ra.full_attention_reference(q, k, v, causal=True)
                       * g_out)

    got = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    expected = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(got, expected, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=5e-5, atol=5e-6, err_msg=name)


def test_bf16_inputs(qkv):
    q, k, v = (x.astype(jnp.bfloat16) for x in qkv)
    mesh = _seq_mesh(4)
    got = jax.jit(lambda q, k, v: ra.ring_attention(
        q, k, v, mesh, "seq", causal=True))(q, k, v)
    assert got.dtype == jnp.bfloat16
    expected = ra.full_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(expected, np.float32),
        rtol=0.05, atol=0.05)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_zigzag_placement_matches_full_attention(qkv, n):
    """Balanced causal placement: permute in, compute, invert out."""
    q, k, v = qkv
    mesh = _seq_mesh(n)
    perm = ra.zigzag_permutation(T, n)
    inv = ra.inverse_zigzag_permutation(T, n)
    expected = ra.full_attention_reference(q, k, v, causal=True)
    out_z = jax.jit(lambda q, k, v: ra.ring_attention(
        q, k, v, mesh, "seq", causal=True, placement="zigzag"))(
        q[:, perm], k[:, perm], v[:, perm])
    got = out_z[:, inv]
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-6)


def test_zigzag_gradients_match(qkv):
    q, k, v = qkv
    n = 4
    mesh = _seq_mesh(n)
    perm = ra.zigzag_permutation(T, n)
    inv = ra.inverse_zigzag_permutation(T, n)
    g_out = jnp.asarray(np.random.default_rng(11).standard_normal(
        (B, T, H, D)).astype(np.float32))

    def zig_loss(q, k, v):
        out = ra.ring_attention(q[:, perm], k[:, perm], v[:, perm],
                                mesh, "seq", causal=True,
                                placement="zigzag")[:, inv]
        return jnp.sum(out * g_out)

    def full_loss(q, k, v):
        return jnp.sum(ra.full_attention_reference(q, k, v, causal=True)
                       * g_out)

    got = jax.jit(jax.grad(zig_loss, argnums=(0, 1, 2)))(q, k, v)
    expected = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(got, expected, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-6, err_msg=name)


def test_zigzag_requires_divisible_T(qkv):
    q, k, v = qkv
    mesh = _seq_mesh(8)
    with pytest.raises(ValueError, match="zigzag"):
        ra.ring_attention(q[:, :24], k[:, :24], v[:, :24], mesh, "seq",
                          causal=True, placement="zigzag")


class TestPallasBlocks:
    """block_impl='pallas': the ring's per-block core runs the flash
    kernels (interpret mode on CPU) and the (out, lse) merge is exact."""

    @pytest.mark.parametrize("placement,causal", [
        ("contiguous", False), ("contiguous", True), ("zigzag", True)])
    def test_matches_full_attention(self, qkv, placement, causal):
        q, k, v = qkv
        n = 4
        mesh = _seq_mesh(n)
        if placement == "zigzag":
            perm = ra.zigzag_permutation(T, n)
            inv = ra.inverse_zigzag_permutation(T, n)
            args = (q[:, perm], k[:, perm], v[:, perm])
        else:
            args = (q, k, v)
        expected = ra.full_attention_reference(q, k, v, causal=causal)
        got = jax.jit(lambda q, k, v: ra.ring_attention(
            q, k, v, mesh, "seq", causal=causal, placement=placement,
            block_impl="pallas"))(*args)
        if placement == "zigzag":
            got = got[:, inv]
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)

    def test_gradients_match_xla_blocks(self, qkv):
        """The lse-cotangent path through the flash backward kernels:
        grads of the pallas-block ring must match the xla-block ring."""
        q, k, v = qkv
        mesh = _seq_mesh(4)
        g_out = jnp.asarray(np.random.default_rng(5).standard_normal(
            (B, T, H, D)).astype(np.float32))

        def loss(impl):
            def f(q, k, v):
                return jnp.sum(ra.ring_attention(
                    q, k, v, mesh, "seq", causal=True,
                    block_impl=impl) * g_out)
            return f

        got = jax.jit(jax.grad(loss("pallas"), argnums=(0, 1, 2)))(
            q, k, v)
        expected = jax.jit(jax.grad(loss("xla"), argnums=(0, 1, 2)))(
            q, k, v)
        for g, e, name in zip(got, expected, "qkv"):
            np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                       rtol=5e-4, atol=5e-5, err_msg=name)

    def test_zigzag_gradients_match_xla_blocks(self, qkv):
        q, k, v = qkv
        n = 4
        mesh = _seq_mesh(n)
        perm = ra.zigzag_permutation(T, n)
        q, k, v = q[:, perm], k[:, perm], v[:, perm]
        g_out = jnp.asarray(np.random.default_rng(6).standard_normal(
            (B, T, H, D)).astype(np.float32))

        def loss(impl):
            def f(q, k, v):
                return jnp.sum(ra.ring_attention(
                    q, k, v, mesh, "seq", causal=True,
                    placement="zigzag", block_impl=impl) * g_out)
            return f

        got = jax.jit(jax.grad(loss("pallas"), argnums=(0, 1, 2)))(
            q, k, v)
        expected = jax.jit(jax.grad(loss("xla"), argnums=(0, 1, 2)))(
            q, k, v)
        for g, e, name in zip(got, expected, "qkv"):
            np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                       rtol=5e-4, atol=5e-5, err_msg=name)
