"""export_graph_path + misc engine behaviors."""

import glob
import os

import numpy as np

import parallax_tpu as parallax
from parallax_tpu.models import simple


def test_export_graph_path_writes_stablehlo(tmp_path, rng):
    export_dir = str(tmp_path / "graph")
    cfg = parallax.Config(run_option="AR", search_partitions=False,
                          export_graph_path=export_dir)
    sess, *_ = parallax.parallel_run(simple.build_model(),
                                     parallax_config=cfg)
    b = simple.make_batch(rng, 64)
    sess.run(None, feed_dict=b)
    sess.run(None, feed_dict=b)
    files = glob.glob(os.path.join(export_dir, "*"))
    assert files, "no graph exported"
    text = open(files[0]).read()
    assert "stablehlo" in text or "module" in text
    sess.close()


def test_unused_knobs_logged_not_fatal(rng):
    cfg = parallax.Config(run_option="AR", search_partitions=False)
    cfg.communication_config.ps_config.protocol = "grpc+verbs"
    cfg.communication_config.mpi_config.mpirun_options = "-x FOO"
    sess, *_ = parallax.parallel_run(simple.build_model(),
                                     parallax_config=cfg)
    loss = sess.run("loss", feed_dict=simple.make_batch(rng, 64))
    assert np.isfinite(loss)
    sess.close()


def test_debug_nans_raises_at_source(rng):
    """Config.debug_nans: a NaN-producing model raises instead of
    silently training on NaNs (sanitizer capability, SURVEY.md §5.2)."""
    import jax
    import jax.numpy as jnp
    import optax

    def init_fn(r):
        return {"w": jnp.ones((4,))}

    def loss_fn(params, batch):
        return jnp.mean(jnp.log(params["w"] * batch["x"]))  # log(neg)->nan

    model = parallax.Model(init_fn, loss_fn, optimizer=optax.sgd(0.1))
    cfg = parallax.Config(run_option="AR", search_partitions=False,
                          debug_nans=True)
    sess, *_ = parallax.parallel_run(model, parallax_config=cfg)
    with np.testing.assert_raises(Exception):
        sess.run("loss",
                 feed_dict={"x": -np.ones((8, 4), np.float32)})
    sess.close()
    # close() restores the process-global flag (no leak into later
    # sessions)
    assert not jax.config.jax_debug_nans


def test_steps_per_sec_metric(rng):
    sess, *_ = parallax.parallel_run(
        simple.build_model(),
        parallax_config=parallax.Config(run_option="AR",
                                        search_partitions=False))
    assert sess.steps_per_sec is None
    for _ in range(5):
        sess.run("loss", feed_dict=simple.make_batch(rng, 64))
    assert sess.steps_per_sec > 0
    sess.close()
