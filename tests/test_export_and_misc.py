"""export_graph_path + misc engine behaviors."""

import glob
import os

import numpy as np

import parallax_tpu as parallax
from parallax_tpu.models import simple


def test_export_graph_path_writes_stablehlo(tmp_path, rng):
    export_dir = str(tmp_path / "graph")
    cfg = parallax.Config(run_option="AR", search_partitions=False,
                          export_graph_path=export_dir)
    sess, *_ = parallax.parallel_run(simple.build_model(),
                                     parallax_config=cfg)
    b = simple.make_batch(rng, 64)
    sess.run(None, feed_dict=b)
    sess.run(None, feed_dict=b)
    files = glob.glob(os.path.join(export_dir, "*"))
    assert files, "no graph exported"
    text = open(files[0]).read()
    assert "stablehlo" in text or "module" in text
    sess.close()


def test_unused_knobs_logged_not_fatal(rng):
    cfg = parallax.Config(run_option="AR", search_partitions=False)
    cfg.communication_config.ps_config.protocol = "grpc+verbs"
    cfg.communication_config.mpi_config.mpirun_options = "-x FOO"
    sess, *_ = parallax.parallel_run(simple.build_model(),
                                     parallax_config=cfg)
    loss = sess.run("loss", feed_dict=simple.make_batch(rng, 64))
    assert np.isfinite(loss)
    sess.close()
