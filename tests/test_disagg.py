"""Disaggregated prefill/decode serving (ISSUE 19): the two-pool
front door, the page-transfer wire protocol, and its failure ladder.

Four layers of coverage:

* the wire format as a PURE unit — export/import roundtrips a nested
  request state exactly (dtype + shape + bits), and malformed states
  (non-dict, '/'-bearing keys) are refused loudly at export;
* the transfer pin (prefixcache ``begin_transfer``/``end_transfer``)
  as a PURE unit — a transferring entry survives LRU pressure, and a
  supersede-during-transfer cannot return the streaming pages to the
  pool until the bracket closes;
* the serving acceptance bar — disaggregated tokens are BIT-IDENTICAL
  to the colocated fleet and to standalone greedy, with zero
  serve-time recompiles across both pools, zero leaked decode pages,
  and the ``kv_transfer``-extended TTFT decomposition summing to the
  client-observed TTFT within 5%;
* the failure ladder — a prefill replica killed mid-transfer fails
  over inside the pool (all requests complete, ``prefill_failovers``
  counts the hop), and a prefill pool with nothing placeable falls
  back to colocated serving (identical tokens, ``prefill_fallbacks``
  counts the degrade).
"""

import time

import numpy as np
import pytest

import parallax_tpu as parallax
from parallax_tpu import ServeConfig
from parallax_tpu.serve import (DisaggFleet, FaultInjector, FleetConfig,
                                PageAllocator, RadixPrefixCache,
                                ServeFleet, ServeSession,
                                export_prefill, import_prefill,
                                registered_adapters, standalone_greedy)
from test_adapters import _build, _feeds

SPEC = registered_adapters()["causal_lm"]


def _mk_factory(prog, params):
    cfg = parallax.Config(serve_config=ServeConfig(
        max_batch=2, max_queue=64, prefix_cache=True))

    def mk(rid, **kw):
        return ServeSession(program=prog, params=params, config=cfg,
                            **kw)
    return mk


# -- the wire protocol as a pure unit ---------------------------------------


class TestWireProtocol:
    def test_roundtrip_nested_exact(self):
        import jax.numpy as jnp
        rs = {"pk": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
              "meta": {"base": np.int32(5),
                       "mask": np.array([True, False])},
              "first": np.arange(3, dtype=np.int32)}
        wire = export_prefill(rs)
        assert isinstance(wire, bytes) and len(wire) > 0
        back = import_prefill(wire)
        assert set(back) == {"pk", "meta", "first"}
        assert set(back["meta"]) == {"base", "mask"}
        np.testing.assert_array_equal(back["pk"], np.asarray(rs["pk"]))
        np.testing.assert_array_equal(back["meta"]["base"], 5)
        np.testing.assert_array_equal(back["meta"]["mask"],
                                      [True, False])
        assert back["pk"].dtype == np.float32
        assert back["first"].dtype == np.int32

    def test_slash_key_refused(self):
        with pytest.raises(ValueError, match="separator"):
            export_prefill({"a/b": np.zeros(2)})

    def test_non_dict_state_refused(self):
        with pytest.raises(ValueError, match="dict of arrays"):
            export_prefill(np.zeros(2))


# -- the transfer pin as a pure unit ----------------------------------------


class TestTransferPin:
    def _entry(self, cache, alloc, key, tokens, n_pages):
        pages = alloc.alloc(n_pages)
        assert cache.insert(None, key, tokens, pages,
                            {"x": np.zeros(1)})
        return cache.lookup(None, key)

    def test_transferring_entry_survives_lru_pressure(self):
        a = PageAllocator(8)
        c = RadixPrefixCache(a)
        streaming = self._entry(c, a, (1,), [9], 4)
        self._entry(c, a, (2,), [9], 4)
        c.begin_transfer(streaming)
        # pool exhausted; eviction may only take the unpinned entry
        assert c.evict_for(4) == 1
        assert c.lookup(None, (1,)) is streaming, \
            "a transferring entry must never be the LRU victim"
        assert c.lookup(None, (2,)) is None
        c.end_transfer(streaming)
        assert c.evict_for(8) == 1
        assert a.in_use == 0

    def test_supersede_cannot_free_transferring_pages(self):
        """The satellite-6 pin: begin_transfer takes a page REF, so a
        longer continuation superseding the entry mid-stream drops only
        the cache's refs — the bytes on the wire keep their backing
        pages until end_transfer."""
        a = PageAllocator(8)
        c = RadixPrefixCache(a)
        streaming = self._entry(c, a, (1,), [7, 8], 4)
        c.begin_transfer(streaming)
        assert a.shared_pages == 4
        # a longer continuation of the same key supersedes mid-stream
        pages2 = a.alloc(4)
        assert c.insert(None, (1,), [7, 8, 9], pages2,
                        {"x": np.zeros(1)})
        assert c.lookup(None, (1,)) is not streaming
        assert a.in_use == 8, \
            "superseded-but-transferring pages must stay allocated"
        c.end_transfer(streaming)
        assert a.in_use == 4, \
            "end_transfer releases the transfer refs"
        assert c.evict_for(8) == 1
        assert a.in_use == 0

    def test_unbalanced_end_transfer_refused(self):
        a = PageAllocator(4)
        c = RadixPrefixCache(a)
        e = self._entry(c, a, (1,), [5], 2)
        with pytest.raises(ValueError, match="begin_transfer"):
            c.end_transfer(e)


# -- serving acceptance: bit-identity + TTFT decomposition ------------------


class TestDisaggServing:
    def test_bit_identical_to_colocated_with_kv_transfer_decomp(self):
        prog, params = _build("causal_lm", True, False)
        mk = _mk_factory(prog, params)
        feeds = _feeds("causal_lm", 5, seed=21)
        want = [standalone_greedy(prog, params, f, max_new_tokens=6)
                for f in feeds]

        colo = ServeFleet(mk, config=FleetConfig(num_replicas=1,
                                                 min_replicas=1))
        try:
            got_colo = [[int(t) for t in r.result(timeout=120)]
                        for r in [colo.submit(f, max_new_tokens=6)
                                  for f in feeds]]
        finally:
            colo.close()
        assert got_colo == want

        d = DisaggFleet(
            mk, mk,
            prefill_config=FleetConfig(num_replicas=1, min_replicas=1),
            decode_config=FleetConfig(num_replicas=1, min_replicas=1))
        try:
            reqs = [d.submit(f, max_new_tokens=6) for f in feeds]
            got = [[int(t) for t in r.result(timeout=120)]
                   for r in reqs]
        finally:
            d.close()
        assert got == want, "disaggregated must be bit-identical"

        snap = d.metrics.snapshot()
        assert snap["serve.disagg.transfers"] == len(feeds)
        assert snap["serve.disagg.transfer_bytes"] > 0
        assert d.recompiles() == 0
        # every decode replica drained back to zero mapped pages
        for rid, st in d.decode_fleet.stats()["replicas"].items():
            assert st["serve"].get("serve.kv_pages_in_use") == 0, rid

        # TTFT decomposition: the kv_transfer phase appears and the
        # phase sum still partitions the client-observed TTFT
        recs = [r for r in d.request_records()
                if r.get("ttft_decomp") is not None]
        assert recs, "front-door records must carry decompositions"
        for rec in recs:
            decomp = rec["ttft_decomp"]
            assert "kv_transfer_ms" in decomp, decomp
            total = sum(decomp.values())
            ttft = rec["ttft_ms"]
            assert abs(total - ttft) <= 0.05 * max(ttft, 1e-9), \
                (total, ttft, decomp)

    def test_prefill_replica_killed_mid_transfer_fails_over(self):
        """The chaos case: one of two prefill replicas dies inside the
        prefill/export path; every request still completes with
        identical tokens via a counted failover hop."""
        prog, params = _build("causal_lm", True, False)
        mk = _mk_factory(prog, params)
        feeds = _feeds("causal_lm", 4, seed=23)
        want = [standalone_greedy(prog, params, f, max_new_tokens=6)
                for f in feeds]
        inj = FaultInjector()
        d = DisaggFleet(
            mk, mk,
            prefill_config=FleetConfig(num_replicas=2, min_replicas=1,
                                       max_retries=2),
            decode_config=FleetConfig(num_replicas=1, min_replicas=1),
            faults=inj)
        try:
            # warm one request end to end first
            assert [int(t) for t in
                    d.submit(feeds[0],
                             max_new_tokens=6).result(timeout=120)] \
                == want[0]
            # park replica 0's idle decode loop inside an injected
            # stall so the one-shot crash is consumed by the PREFILL
            # path (the mid-transfer kill), not by an idle tick
            inj.arm(0, "stall", seconds=2.0)
            t_end = time.perf_counter() + 2.0
            while inj.fired("stall") == 0 \
                    and time.perf_counter() < t_end:
                time.sleep(0.005)
            assert inj.fired("stall") == 1
            inj.arm(0, "crash")
            reqs = [d.submit(f, max_new_tokens=6) for f in feeds]
            got = [[int(t) for t in r.result(timeout=120)]
                   for r in reqs]
        finally:
            d.close()
        assert got == want, "failover must not change a single token"
        assert inj.fired("crash") == 1
        snap = d.metrics.snapshot()
        assert snap["serve.disagg.prefill_failovers"] >= 1, snap
        assert d.recompiles() == 0

    def test_dead_prefill_pool_falls_back_to_colocated(self):
        """Bottom of the failure ladder: nothing placeable in the
        prefill pool degrades to colocated serving — the decode
        replica's admission misses the cache and runs the prefill
        locally, tokens unchanged."""
        prog, params = _build("causal_lm", True, False)
        mk = _mk_factory(prog, params)
        feeds = _feeds("causal_lm", 3, seed=29)
        want = [standalone_greedy(prog, params, f, max_new_tokens=6)
                for f in feeds]
        inj = FaultInjector()
        d = DisaggFleet(
            mk, mk,
            prefill_config=FleetConfig(num_replicas=1, min_replicas=1),
            decode_config=FleetConfig(num_replicas=1, min_replicas=1),
            faults=inj)
        try:
            inj.arm(0, "crash")  # the idle tick takes it: replica dies
            t_end = time.perf_counter() + 5.0
            while d.prefill_fleet.live_sessions() \
                    and time.perf_counter() < t_end:
                time.sleep(0.01)
            assert not d.prefill_fleet.live_sessions()
            reqs = [d.submit(f, max_new_tokens=6) for f in feeds]
            got = [[int(t) for t in r.result(timeout=120)]
                   for r in reqs]
        finally:
            d.close()
        assert got == want, "the fallback path must be bit-identical"
        snap = d.metrics.snapshot()
        assert snap["serve.disagg.prefill_fallbacks"] == len(feeds)
        assert snap["serve.disagg.transfers"] == 0
