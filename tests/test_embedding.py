"""Tests for the row-sharded embedding op (ops/embedding.py).

Numerics parity targets: forward lookup == plain take; backward ==
scatter-add (sum) or the reference fork's SPARSE_AVERAGE_BY_COUNTER
(average duplicate updates by global occurrence count,
graph_transform_lib.py:101-102).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parallax_tpu.core import mesh as mesh_lib
from parallax_tpu.ops import embedding


V, D, B = 32, 8, 16


@pytest.fixture
def table(rng):
    return jnp.asarray(rng.standard_normal((V, D)).astype(np.float32))


@pytest.fixture
def ids(rng):
    # include duplicates deliberately
    return jnp.asarray(rng.integers(0, V, size=(B,)) % V, dtype=jnp.int32
                       ).at[0].set(3).at[1].set(3).at[2].set(3)


def _ctx(num_partitions, avg=False):
    mesh = mesh_lib.build_mesh(num_partitions=num_partitions)
    return mesh, embedding.sharded_lookup_scope(mesh, [(V, D)], avg)


@pytest.mark.parametrize("p", [2, 4, 8])
def test_forward_matches_plain_take(table, ids, p):
    mesh, scope = _ctx(p)
    expected = jnp.take(table, ids, axis=0)

    with scope:
        @jax.jit
        def f(t, i):
            return embedding.embedding_lookup(t, i)
        out = f(table, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-6)


def test_forward_2d_ids(table, rng):
    ids2 = jnp.asarray(rng.integers(0, V, size=(8, 4)), dtype=jnp.int32)
    mesh, scope = _ctx(4)
    with scope:
        out = jax.jit(
            lambda t, i: embedding.embedding_lookup(t, i))(table, ids2)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jnp.take(table, ids2, axis=0)),
                               rtol=1e-6)


def test_unregistered_shape_uses_plain_gather(table, ids):
    mesh, _ = _ctx(4)
    with embedding.sharded_lookup_scope(mesh, [(999, 1)], False):
        out = jax.jit(
            lambda t, i: embedding.embedding_lookup(t, i))(table, ids)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jnp.take(table, ids, axis=0)),
                               rtol=1e-6)


@pytest.mark.parametrize("p", [2, 8])
def test_backward_sum_matches_dense_scatter_add(table, ids, p):
    mesh, scope = _ctx(p)
    g_out = jnp.ones((B, D), jnp.float32) * 0.5

    def ref_loss(t):
        return jnp.sum(jnp.take(t, ids, axis=0) * g_out)

    expected = jax.grad(ref_loss)(table)

    with scope:
        def loss(t):
            return jnp.sum(embedding.embedding_lookup(t, ids) * g_out)
        got = jax.jit(jax.grad(loss))(table)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5)


def test_backward_average_by_counter(table, ids):
    """Duplicate ids: gradient rows divided by global occurrence count
    (SPARSE_AVERAGE_BY_COUNTER parity)."""
    mesh, scope = _ctx(4, avg=True)
    g_rows = jnp.asarray(
        np.random.default_rng(7).standard_normal((B, D)).astype(np.float32))

    def ref_grad():
        dense = jnp.zeros((V, D)).at[ids].add(g_rows)
        counts = jnp.zeros((V,)).at[ids].add(1.0)
        return dense / jnp.maximum(counts, 1.0)[:, None]

    with scope:
        def loss(t):
            return jnp.sum(embedding.embedding_lookup(t, ids) * g_rows)
        got = jax.jit(jax.grad(loss))(table)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref_grad()),
                               rtol=1e-5, atol=1e-6)


def test_pad_vocab():
    assert embedding.pad_vocab(793470, 8) == 793472
    assert embedding.pad_vocab(16, 8) == 16
    assert embedding.pad_vocab(17, 8) == 24


def test_p1_degenerates_to_plain_take(table, ids):
    mesh, scope = _ctx(1)
    with scope:
        out = jax.jit(
            lambda t, i: embedding.embedding_lookup(t, i))(table, ids)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jnp.take(table, ids, axis=0)))
