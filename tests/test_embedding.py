"""Tests for the row-sharded embedding op (ops/embedding.py).

Numerics parity targets: forward lookup == plain take; backward ==
scatter-add (sum) or the reference fork's SPARSE_AVERAGE_BY_COUNTER
(average duplicate updates by global occurrence count,
graph_transform_lib.py:101-102).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parallax_tpu.core import mesh as mesh_lib
from parallax_tpu.ops import embedding


V, D, B = 32, 8, 16


@pytest.fixture
def table(rng):
    return jnp.asarray(rng.standard_normal((V, D)).astype(np.float32))


@pytest.fixture
def ids(rng):
    # include duplicates deliberately
    return jnp.asarray(rng.integers(0, V, size=(B,)) % V, dtype=jnp.int32
                       ).at[0].set(3).at[1].set(3).at[2].set(3)


def _ctx(num_partitions, avg=False):
    mesh = mesh_lib.build_mesh(num_partitions=num_partitions)
    return mesh, embedding.sharded_lookup_scope(mesh, [(V, D)], avg)


@pytest.mark.parametrize("p", [2, 4, 8])
def test_forward_matches_plain_take(table, ids, p):
    mesh, scope = _ctx(p)
    expected = jnp.take(table, ids, axis=0)

    with scope:
        @jax.jit
        def f(t, i):
            return embedding.embedding_lookup(t, i)
        out = f(table, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-6)


def test_forward_2d_ids(table, rng):
    ids2 = jnp.asarray(rng.integers(0, V, size=(8, 4)), dtype=jnp.int32)
    mesh, scope = _ctx(4)
    with scope:
        out = jax.jit(
            lambda t, i: embedding.embedding_lookup(t, i))(table, ids2)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jnp.take(table, ids2, axis=0)),
                               rtol=1e-6)


def test_unregistered_shape_uses_plain_gather(table, ids):
    mesh, _ = _ctx(4)
    with embedding.sharded_lookup_scope(mesh, [(999, 1)], False):
        out = jax.jit(
            lambda t, i: embedding.embedding_lookup(t, i))(table, ids)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jnp.take(table, ids, axis=0)),
                               rtol=1e-6)


@pytest.mark.parametrize("p", [2, 8])
def test_backward_sum_matches_dense_scatter_add(table, ids, p):
    mesh, scope = _ctx(p)
    g_out = jnp.ones((B, D), jnp.float32) * 0.5

    def ref_loss(t):
        return jnp.sum(jnp.take(t, ids, axis=0) * g_out)

    expected = jax.grad(ref_loss)(table)

    with scope:
        def loss(t):
            return jnp.sum(embedding.embedding_lookup(t, ids) * g_out)
        got = jax.jit(jax.grad(loss))(table)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5)


def test_backward_average_by_counter(table, ids):
    """Duplicate ids: gradient rows divided by global occurrence count
    (SPARSE_AVERAGE_BY_COUNTER parity)."""
    mesh, scope = _ctx(4, avg=True)
    g_rows = jnp.asarray(
        np.random.default_rng(7).standard_normal((B, D)).astype(np.float32))

    def ref_grad():
        dense = jnp.zeros((V, D)).at[ids].add(g_rows)
        counts = jnp.zeros((V,)).at[ids].add(1.0)
        return dense / jnp.maximum(counts, 1.0)[:, None]

    with scope:
        def loss(t):
            return jnp.sum(embedding.embedding_lookup(t, ids) * g_rows)
        got = jax.jit(jax.grad(loss))(table)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref_grad()),
                               rtol=1e-5, atol=1e-6)


def test_pad_vocab():
    assert embedding.pad_vocab(793470, 8) == 793472
    assert embedding.pad_vocab(16, 8) == 16
    assert embedding.pad_vocab(17, 8) == 24


class TestLocalAggregationDedup:
    """Two-stage combine (local_aggregation): unique-id compression is
    active when vocab < per-device ids, cuts wire bytes, and never
    changes numerics (reference graph_transform_lib.py:1372-1556)."""

    SV, SD, SB = 8, 4, 128  # vocab 8 << per-device ids 16 on the 8-mesh

    def _zipf_ids(self, rng):
        raw = np.minimum(rng.zipf(1.5, size=(self.SB,)) - 1, self.SV - 1)
        return jnp.asarray(raw, dtype=jnp.int32)

    def _scope(self, p, avg, local_agg, records=None):
        mesh = mesh_lib.build_mesh(num_partitions=p)
        return embedding.sharded_lookup_scope(
            mesh, [(self.SV, self.SD)], avg, records=records,
            local_aggregation=local_agg)

    @pytest.mark.parametrize("avg", [False, True])
    @pytest.mark.parametrize("local_agg", [False, True])
    def test_numerics_unchanged(self, rng, avg, local_agg):
        table = jnp.asarray(
            rng.standard_normal((self.SV, self.SD)).astype(np.float32))
        ids = self._zipf_ids(rng)
        g_rows = jnp.asarray(rng.standard_normal(
            (self.SB, self.SD)).astype(np.float32))

        def ref_fwd():
            return jnp.take(table, ids, axis=0)

        def ref_grad():
            dense = jnp.zeros((self.SV, self.SD)).at[ids].add(g_rows)
            if not avg:
                return dense
            counts = jnp.zeros((self.SV,)).at[ids].add(1.0)
            return dense / jnp.maximum(counts, 1.0)[:, None]

        with self._scope(4, avg, local_agg):
            def loss(t):
                return jnp.sum(embedding.embedding_lookup(t, ids) * g_rows)
            out = jax.jit(
                lambda t: embedding.embedding_lookup(t, ids))(table)
            got = jax.jit(jax.grad(loss))(table)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_fwd()),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref_grad()),
                                   rtol=1e-4, atol=1e-6)

    def test_wire_bytes_shrink_on_zipf_batch(self, rng):
        table = jnp.asarray(
            rng.standard_normal((self.SV, self.SD)).astype(np.float32))
        ids = self._zipf_ids(rng)
        counts = {}
        for local_agg in (False, True):
            records = []
            with self._scope(4, False, local_agg, records=records):
                jax.jit(lambda t:
                        embedding.embedding_lookup(t, ids))(table)
            (_, n_eff, *_), = records
            counts[local_agg] = n_eff
        assert counts[False] == self.SB
        # capacity min(local ids 16, vocab+1 = 9) = 9 slots x 8 devices
        assert counts[True] == (self.SV + 1) * 8
        assert counts[True] < counts[False]

    @pytest.mark.parametrize("avg", [False, True])
    def test_sentinel_ids_exact_under_dedup(self, rng, avg):
        """Out-of-range ids (padding sentinels) must keep yielding zero
        rows / dropped grads even when they push the distinct-value count
        past the vocab size (the capacity bound collapses them to one
        sentinel first)."""
        table = jnp.asarray(
            rng.standard_normal((self.SV, self.SD)).astype(np.float32))
        # every vocab id present on each device, PLUS -1 and V sentinels
        base = np.tile(np.arange(self.SV, dtype=np.int32),
                       self.SB // self.SV)
        base[::7] = -1
        base[3::11] = self.SV
        ids = jnp.asarray(base)
        g_rows = jnp.asarray(rng.standard_normal(
            (self.SB, self.SD)).astype(np.float32))

        results = {}
        for local_agg in (False, True):
            with self._scope(4, avg, local_agg):
                def loss(t):
                    return jnp.sum(
                        embedding.embedding_lookup(t, ids) * g_rows)
                out = jax.jit(
                    lambda t: embedding.embedding_lookup(t, ids))(table)
                grad = jax.jit(jax.grad(loss))(table)
            results[local_agg] = (np.asarray(out), np.asarray(grad))
        np.testing.assert_allclose(results[True][0], results[False][0],
                                   rtol=1e-5)
        np.testing.assert_allclose(results[True][1], results[False][1],
                                   rtol=1e-4, atol=1e-6)
        # sentinel positions yield zero rows
        assert np.all(results[True][0][np.asarray(ids) < 0] == 0.0)

    def test_large_vocab_skips_dedup(self, rng):
        """vocab >= per-device ids: compression cannot win, raw path."""
        table = jnp.asarray(
            rng.standard_normal((V, D)).astype(np.float32))
        ids = jnp.asarray(rng.integers(0, V, size=(B,)), dtype=jnp.int32)
        records = []
        mesh = mesh_lib.build_mesh(num_partitions=4)
        with embedding.sharded_lookup_scope(mesh, [(V, D)], False,
                                            records=records,
                                            local_aggregation=True):
            jax.jit(lambda t: embedding.embedding_lookup(t, ids))(table)
        (_, n_eff, *_), = records
        assert n_eff == B


class TestDeclaredDedupCapacity:
    """PSConfig.dedup_capacity: user-declared slot count below the
    automatic exactness bound. Compresses Zipf batches the automatic
    bound cannot (vocab > per-device ids); overflow steps fall back to
    the exact uncompressed exchange — capacity is a wire-size target,
    never a correctness risk."""

    CV, CD, CB = 64, 4, 128  # vocab 64 > per-device ids 16 on the 8-mesh

    def _scope(self, avg, cap, records=None):
        mesh = mesh_lib.build_mesh(num_partitions=4)
        return embedding.sharded_lookup_scope(
            mesh, [(self.CV, self.CD)], avg, records=records,
            local_aggregation=True, dedup_capacity=cap)

    def _run(self, table, ids, g_rows, avg, cap):
        with self._scope(avg, cap):
            def loss(t):
                return jnp.sum(
                    embedding.embedding_lookup(t, ids) * g_rows)
            out = jax.jit(
                lambda t: embedding.embedding_lookup(t, ids))(table)
            grad = jax.jit(jax.grad(loss))(table)
        return np.asarray(out), np.asarray(grad)

    @pytest.mark.parametrize("avg", [False, True])
    def test_exact_under_and_over_capacity(self, rng, avg):
        table = jnp.asarray(
            rng.standard_normal((self.CV, self.CD)).astype(np.float32))
        g_rows = jnp.asarray(rng.standard_normal(
            (self.CB, self.CD)).astype(np.float32))
        # Zipf batch: few distinct ids per device -> capacity 8 holds
        zipf = jnp.asarray(np.minimum(rng.zipf(1.8, size=(self.CB,)) - 1,
                                      self.CV - 1), dtype=jnp.int32)
        # adversarial batch: every device sees 16 distinct ids -> the
        # declared capacity 8 overflows and the exact fallback engages
        spread = jnp.asarray(np.arange(self.CB) % self.CV,
                             dtype=jnp.int32)
        for ids in (zipf, spread):
            ref_out, ref_grad = self._run(table, ids, g_rows, avg, None)
            got_out, got_grad = self._run(table, ids, g_rows, avg, 8)
            np.testing.assert_allclose(got_out, ref_out, rtol=1e-5)
            np.testing.assert_allclose(got_grad, ref_grad, rtol=1e-4,
                                       atol=1e-6)

    def test_declared_capacity_cuts_recorded_wire_bytes(self, rng):
        table = jnp.asarray(
            rng.standard_normal((self.CV, self.CD)).astype(np.float32))
        ids = jnp.asarray(np.minimum(rng.zipf(1.8, size=(self.CB,)) - 1,
                                     self.CV - 1), dtype=jnp.int32)
        counts = {}
        for cap in (None, 8):
            records = []
            with self._scope(False, cap, records=records):
                jax.jit(lambda t:
                        embedding.embedding_lookup(t, ids))(table)
            (_, n_eff, *_), = records
            counts[cap] = n_eff
        # automatic bound min(16, 65) = 16 = per-device ids: no win
        assert counts[None] == self.CB
        assert counts[8] == 8 * 8  # declared capacity x 8 devices
        assert counts[8] < counts[None]

    def test_capacity_at_or_above_bound_unguarded(self):
        """Hints at/above the automatic bound degrade gracefully."""
        mesh = mesh_lib.build_mesh(num_partitions=4)
        # vocab 8, local ids 16: auto bound 9; hint 32 clamps to 9
        cap, guarded = embedding._dedup_capacity(
            (8, 4), (128,), mesh, True, hint=32)
        assert (cap, guarded) == (9, False)
        # hint below the bound: guarded
        cap, guarded = embedding._dedup_capacity(
            (8, 4), (128,), mesh, True, hint=4)
        assert (cap, guarded) == (4, True)
        # hint >= local ids on a big vocab: no compression possible
        cap, guarded = embedding._dedup_capacity(
            (64, 4), (128,), mesh, True, hint=16)
        assert (cap, guarded) == (None, False)


class TestSparseCrossReplicaCombine:
    """Cross-replica table-grad combine: gathering only the deduped
    (ids, row-grads) over 'repl' vs the dense [rows/shard, dim] psum —
    numerics identical either way, chosen statically by bytes."""

    XD, XB = 4, 128  # p=4, r=2 on the 8-device mesh; 16 ids/device

    def _scope(self, vocab, avg, xrepl, records=None):
        mesh = mesh_lib.build_mesh(num_partitions=4)
        assert mesh.shape["repl"] == 2
        return embedding.sharded_lookup_scope(
            mesh, [(vocab, self.XD)], avg, records=records,
            local_aggregation=True, cross_replica_sparse=xrepl)

    # vocab 8 < 16 ids/device: the dedup stage engages (compressed
    # gather + shipped counts); vocab 64: raw full-id gather
    @pytest.mark.parametrize("vocab", [8, 64])
    @pytest.mark.parametrize("avg", [False, True])
    def test_parity_forced_sparse_vs_dense(self, rng, avg, vocab):
        table = jnp.asarray(
            rng.standard_normal((vocab, self.XD)).astype(np.float32))
        ids = jnp.asarray(rng.integers(0, vocab, size=(self.XB,)),
                          dtype=jnp.int32)
        g_rows = jnp.asarray(rng.standard_normal(
            (self.XB, self.XD)).astype(np.float32))

        grads = {}
        for xrepl in (False, True):
            with self._scope(vocab, avg, xrepl):
                def loss(t):
                    return jnp.sum(
                        embedding.embedding_lookup(t, ids) * g_rows)
                grads[xrepl] = np.asarray(jax.jit(jax.grad(loss))(table))
        np.testing.assert_allclose(grads[True], grads[False],
                                   rtol=1e-4, atol=1e-6)

    def test_accounting_reflects_choice(self, rng):
        vocab = 64
        table = jnp.asarray(
            rng.standard_normal((vocab, self.XD)).astype(np.float32))
        ids = jnp.asarray(rng.integers(0, vocab, size=(self.XB,)),
                          dtype=jnp.int32)
        repl_bytes = {}
        for xrepl in (False, True):
            records = []
            with self._scope(vocab, False, xrepl, records=records):
                jax.jit(lambda t:
                        embedding.embedding_lookup(t, ids))(table)
            (_, _, _, rb, *_), = records
            repl_bytes[xrepl] = rb
        assert repl_bytes[False] > 0  # dense psum cost visible
        assert repl_bytes[True] > 0
        assert repl_bytes[True] != repl_bytes[False]

    def test_auto_chooser_by_bytes(self):
        mesh = mesh_lib.build_mesh(num_partitions=4)
        # big vocab, few ids: sparse gather beats dense psum
        assert embedding._choose_sparse_repl(
            mesh, (1 << 20, 64), cap_eff=128, counts=False, hint=None)
        # tiny vocab, many ids: dense psum cheaper
        assert not embedding._choose_sparse_repl(
            mesh, (16, 4), cap_eff=16, counts=False, hint=None)
        # single repl row: never
        mesh1 = mesh_lib.build_mesh(num_partitions=8)
        assert not embedding._choose_sparse_repl(
            mesh1, (1 << 20, 64), cap_eff=128, counts=False, hint=None)
        # hint forces
        assert embedding._choose_sparse_repl(
            mesh, (16, 4), cap_eff=16, counts=False, hint=True)


def test_p1_degenerates_to_plain_take(table, ids):
    mesh, scope = _ctx(1)
    with scope:
        out = jax.jit(
            lambda t, i: embedding.embedding_lookup(t, i))(table, ids)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jnp.take(table, ids, axis=0)))


class TestPerTableDedupCapacity:
    def test_path_keyed_capacities_compress_only_named_tables(self, rng):
        """PSConfig.dedup_capacity as a path-keyed dict (slices mode):
        the named table ships its declared capacity, unlisted tables
        keep the automatic bound — and the trajectory still matches the
        undeclared run (the guarded combine is exact)."""
        import parallax_tpu as parallax
        from parallax_tpu.models import lm1b

        batches = [lm1b.make_batch(rng, 16, 8, 1000) for _ in range(3)]

        def run(cap):
            cfg = lm1b.tiny_config(num_partitions=8,
                                   sparse_grad_mode="slices")
            comm = parallax.CommunicationConfig(
                ps_config=parallax.PSConfig(dedup_capacity=cap))
            sess, *_ = parallax.parallel_run(
                lm1b.build_model(cfg),
                parallax_config=parallax.Config(
                    run_option="HYBRID", search_partitions=False,
                    sparse_grad_mode="slices",
                    communication_config=comm))
            losses = [float(sess.run("loss", feed_dict=b))
                      for b in batches]
            recs = sess.engine.sparse_wire_bytes_per_step()["per_lookup"]
            sess.close()
            return losses, recs

        base_losses, base_recs = run(None)
        dict_losses, dict_recs = run({"emb": 8})

        # tiny config: 16 ids/device on emb; declaring 8 halves the
        # emb exchange while softmax lookups keep the automatic bound.
        # (Identify the emb record by its declared capacity — emb and
        # softmax_w share shape (V, 32) in tiny_config, so shape-based
        # selection would be ambiguous.)
        by_ids = sorted(r["ids_on_wire"] for r in base_recs)
        by_ids_d = sorted(r["ids_on_wire"] for r in dict_recs)
        assert sum(by_ids_d) < sum(by_ids), (by_ids, by_ids_d)
        at_cap = [r for r in dict_recs if r["ids_on_wire"] == 8 * 8]
        assert len(at_cap) == 1, by_ids_d
        assert not any(r["ids_on_wire"] == 8 * 8 for r in base_recs), \
            by_ids
        # exactness: guarded capacity never changes the math
        np.testing.assert_allclose(dict_losses, base_losses, rtol=1e-4)


def test_flagship_wire_ratio_gate():
    """Regression gate (VERDICT r4 weak item 3 / next item 6): the
    FLAGSHIP sparse path must stay under 2% of the same-dtype dense
    all-reduce, recomputed from the engine's trace-time accounting — a
    lookup regression (lost dedup, widened planes, an extra dense
    cotangent) can't land silently. The committed artifact
    (perf/WIRE_BYTES_r04.json) records 1.3%."""
    import os as _os
    import sys
    sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), ".."))
    from tools.wire_bytes_report import flagship_accounting
    acct = flagship_accounting(8, table_dtype="bfloat16",
                               dedup_capacity="auto")
    assert acct["config"]["dedup_capacity_overflow_free"] is True
    ratio = acct["sparse_over_dense"]          # same-dtype, bf16/bf16
    assert ratio is not None and ratio < 0.02, acct
    # and the fp32-reference ratio keeps its documented relationship
    # (exactly half the same-dtype ratio for bf16 tables)
    np.testing.assert_allclose(acct["sparse_over_dense_fp32_ref"],
                               ratio / 2, rtol=1e-9)
