"""Async step pipeline (ISSUE 1): lazy fetches, run_async, run_iter
prefetch — equivalence with the sequential blocking loop (bitwise),
bounded prefetch depth and ordering, exception propagation out of the
prefetch thread, clean shutdown, and a wall-clock overlap win with an
artificially slow feed transform."""

import threading
import time

import numpy as np
import pytest

import parallax_tpu as parallax
from parallax_tpu.data import prefetch_to_device
from parallax_tpu.data.prefetch import Prefetcher
from parallax_tpu.models import simple
from parallax_tpu.session import Fetch, StepHandle


def _simple_session(**cfg_kw):
    sess, *_ = parallax.parallel_run(
        simple.build_model(learning_rate=0.1),
        parallax_config=parallax.Config(run_option="AR",
                                        search_partitions=False,
                                        **cfg_kw))
    return sess


def _batches(n, batch=64, seed=0):
    rng = np.random.default_rng(seed)
    return [simple.make_batch(rng, batch) for _ in range(n)]


# -- Prefetcher unit behavior ---------------------------------------------


class TestPrefetcher:
    def test_order_and_completeness(self):
        with Prefetcher(range(50), lambda x: x * 2, depth=3) as pf:
            assert list(pf) == [2 * i for i in range(50)]

    def test_bounded_depth(self):
        produced = []

        def place(x):
            produced.append(x)
            return x

        out = []
        with Prefetcher(range(30), place, depth=2) as pf:
            for v in pf:
                time.sleep(0.005)  # slow consumer: let the worker race
                # ahead of `out`: the yielded item (1) + queue (depth) +
                # at most one in flight inside place()
                assert len(produced) - len(out) <= 1 + 2 + 1
                out.append(v)
        assert out == list(range(30))

    def test_source_exception_propagates(self):
        def source():
            yield from range(3)
            raise RuntimeError("boom at 3")

        pf = Prefetcher(source(), depth=2)
        got = [next(pf), next(pf), next(pf)]
        assert got == [0, 1, 2]
        with pytest.raises(RuntimeError, match="boom at 3"):
            next(pf)
        # terminal: the failed pipeline stays stopped
        with pytest.raises(StopIteration):
            next(pf)

    def test_place_fn_exception_propagates(self):
        def place(x):
            if x == 2:
                raise ValueError("bad batch 2")
            return x

        pf = Prefetcher(range(10), place, depth=2)
        assert [next(pf), next(pf)] == [0, 1]
        with pytest.raises(ValueError, match="bad batch 2"):
            next(pf)
        pf.close()

    def test_close_stops_worker_promptly(self):
        def endless():
            i = 0
            while True:
                yield i
                i += 1

        pf = Prefetcher(endless(), depth=2)
        assert next(pf) == 0
        assert pf.alive
        pf.close()
        assert not pf.alive
        pf.close()  # idempotent


# -- lazy fetches ----------------------------------------------------------


class TestLazyFetch:
    def test_run_returns_lazy_handles_with_value_semantics(self):
        sess = _simple_session()
        try:
            (b,) = _batches(1)
            loss, step = sess.run(["loss", "global_step"], feed_dict=b)
            assert isinstance(loss, Fetch) and isinstance(step, Fetch)
            # reads materialize: numerics/comparisons/formatting all work
            assert step == 1 and int(step) == 1
            assert np.isfinite(float(loss))
            assert np.isfinite(np.asarray(loss))
            assert 0.5 * loss + 1.0 > 0
            assert "{:.3f}".format(loss)
            assert loss.ndim == 0 and loss.done()
            # dict fetch + single-name fetch keep their shapes
            out = sess.run(None, feed_dict=b)
            assert set(out) >= {"loss", "global_step"}
            assert isinstance(out["loss"], Fetch)
            single = sess.run("loss", feed_dict=b)
            assert isinstance(single, Fetch)
            # materialize() resolves whole structures
            host = parallax.materialize(out)
            assert isinstance(host["loss"], float)
        finally:
            sess.close()

    def test_eager_fetch_restores_blocking_values(self):
        sess = _simple_session(eager_fetch=True)
        try:
            (b,) = _batches(1)
            loss, step = sess.run(["loss", "global_step"], feed_dict=b)
            assert isinstance(loss, float) and step == 1
        finally:
            sess.close()

    def test_lazy_matches_eager_bitwise(self):
        batches = _batches(10)
        eager = _simple_session(eager_fetch=True)
        try:
            want = [eager.run("loss", feed_dict=b) for b in batches]
        finally:
            eager.close()
        lazy = _simple_session()
        try:
            handles = [lazy.run("loss", feed_dict=b) for b in batches]
            got = [float(h) for h in handles]
        finally:
            lazy.close()
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_run_async_handle(self):
        sess = _simple_session()
        try:
            (b,) = _batches(1)
            h = sess.run_async(["loss", "global_step"], feed_dict=b)
            assert isinstance(h, StepHandle)
            loss, step = h.result()
            assert isinstance(loss, float) and step == 1
            assert h.done()
        finally:
            sess.close()


# -- run_iter: pipelined loop ---------------------------------------------


class TestRunIter:
    def test_matches_sequential_run_bitwise_in_order(self):
        batches = _batches(12)
        seq = _simple_session(eager_fetch=True)
        try:
            want = [seq.run(["loss", "global_step"], feed_dict=b)
                    for b in batches]
        finally:
            seq.close()
        pipe = _simple_session(prefetch_depth=3)
        try:
            got = [parallax.materialize(r) for r in
                   pipe.run_iter(batches, ["loss", "global_step"])]
        finally:
            pipe.close()
        assert [s for _, s in got] == list(range(1, 13))  # in order
        np.testing.assert_array_equal(
            np.asarray([l for l, _ in got]),
            np.asarray([l for l, _ in want]))

    def test_placed_batches_roundtrip(self):
        """External pipeline: prefetch_to_device chained onto
        place_batch feeds run_iter(placed=True)."""
        batches = _batches(6)
        seq = _simple_session(eager_fetch=True)
        try:
            want = [seq.run("loss", feed_dict=b) for b in batches]
        finally:
            seq.close()
        sess = _simple_session()
        try:
            # no prepare(): the documented chaining builds the engine
            # lazily on the prefetch thread's first place_batch call
            with prefetch_to_device(batches, sess.place_batch,
                                    depth=2) as placed:
                got = [float(r) for r in
                       sess.run_iter(placed, "loss", placed=True)]
        finally:
            sess.close()
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_iterator_exception_surfaces(self):
        sess = _simple_session()
        try:
            def source():
                yield from _batches(3)
                raise ValueError("feed pipeline died")

            gen = sess.run_iter(source(), "loss")
            got = [next(gen), next(gen), next(gen)]
            assert all(np.isfinite(float(g)) for g in got)
            with pytest.raises(ValueError, match="feed pipeline died"):
                next(gen)
        finally:
            sess.close()

    def test_transform_exception_surfaces_from_prefetch_thread(self):
        model = simple.build_model(learning_rate=0.1)
        calls = []

        def bad_transform(x, mesh):
            calls.append(threading.current_thread().name)
            if len(calls) == 3:
                raise RuntimeError("transform blew up")
            return x

        model.feed_transforms["x"] = bad_transform
        sess, *_ = parallax.parallel_run(
            model, parallax_config=parallax.Config(
                run_option="AR", search_partitions=False))
        try:
            gen = sess.run_iter(_batches(6), "loss")
            got = [next(gen), next(gen)]
            assert all(np.isfinite(float(g)) for g in got)
            with pytest.raises(RuntimeError, match="transform blew up"):
                list(gen)
            # the failing call ran on the prefetch thread, not the
            # dispatch thread
            assert any("prefetch" in name for name in calls)
        finally:
            sess.close()

    def test_close_shuts_down_prefetch_thread(self):
        sess = _simple_session()
        rng = np.random.default_rng(0)

        def endless():
            while True:
                yield simple.make_batch(rng, 64)

        gen = sess.run_iter(endless(), "loss")
        next(gen)
        next(gen)
        pf = sess._prefetcher
        assert pf is not None and pf.alive
        sess.close()
        assert not pf.alive
        gen.close()  # generator finalization after close stays clean
        assert sess._prefetcher is None

    def test_pipeline_stats_populated(self):
        sess = _simple_session()
        try:
            list(sess.run_iter(_batches(8), fetches=[]))
            s = sess.pipeline_stats.summary()
            assert s["steps"] == 8
            assert s["h2d_bytes_per_step"] > 0
            assert s["dispatch"]["mean_ms"] >= 0
            assert s["dispatch_gap"]["mean_ms"] >= 0
        finally:
            sess.close()


# -- the overlap win -------------------------------------------------------


def _heavy_model(dim=256, iters=4):
    """A step heavy enough (tens of ms on the CPU rig) that hiding feed
    prep behind it is measurable."""
    import jax
    import jax.numpy as jnp
    import optax

    def init_fn(rng):
        return {"w": jax.random.normal(rng, (dim, dim),
                                       jnp.float32) * 0.05}

    def loss_fn(params, batch):
        y = batch["x"]
        for _ in range(iters):
            y = jnp.tanh(y @ params["w"])
        return jnp.mean((y - batch["y"]) ** 2)

    return parallax.Model(init_fn, loss_fn, optimizer=optax.sgd(0.01)), dim


class TestOverlap:
    N_STEPS = 10
    SLEEP = 0.03

    def _run_both(self):
        dim_batches = None
        times, losses, prep_starts, mat_done = {}, {}, [], []
        for mode in ("sequential", "pipelined"):
            model, dim = _heavy_model()
            sleep = self.SLEEP

            def slow_transform(x, mesh, _starts=prep_starts,
                               _mode=mode):
                if _mode == "pipelined":
                    _starts.append(time.perf_counter())
                time.sleep(sleep)
                return x

            model.feed_transforms["x"] = slow_transform
            if dim_batches is None:
                rng = np.random.default_rng(3)
                dim_batches = [
                    {"x": rng.standard_normal((64, dim)).astype(
                        np.float32),
                     "y": rng.standard_normal((64, dim)).astype(
                         np.float32)}
                    for _ in range(self.N_STEPS)]
            sess, *_ = parallax.parallel_run(
                model, parallax_config=parallax.Config(
                    run_option="AR", search_partitions=False,
                    eager_fetch=(mode == "sequential")))
            try:
                sess.run("loss", feed_dict=dim_batches[0])  # compile
                t0 = time.perf_counter()
                if mode == "sequential":
                    # the pre-async loop: blocking fetch every step
                    ls = [sess.run("loss", feed_dict=b)
                          for b in dim_batches]
                else:
                    ls = []
                    for f in sess.run_iter(dim_batches, "loss"):
                        ls.append(float(f))  # materialize step t...
                        mat_done.append(time.perf_counter())
                times[mode] = time.perf_counter() - t0
                losses[mode] = [float(x) for x in ls]
            finally:
                sess.close()
        return times, losses, prep_starts, mat_done

    def test_pipelined_overlaps_and_matches_bitwise(self):
        # the wall-time margin is a PERF assertion on a possibly-loaded
        # CI box (typical ratio ~0.55, contended tail ~0.87): give it
        # one retry. Correctness (bitwise equality) must hold on EVERY
        # attempt and never gets a retry.
        last_exc = None
        for _attempt in range(2):
            times, losses, prep_starts, mat_done = self._run_both()
            # identical math: the pipeline reorders WORK, never results
            np.testing.assert_array_equal(
                np.asarray(losses["pipelined"]),
                np.asarray(losses["sequential"]))
            # feed prep for batch t+1 started before step t's result
            # was materialized (true overlap, not just reordering):
            # prep_starts has one entry per batch incl. the
            # compile-step batch. A sequential loop scores 0 here;
            # require a solid majority rather than all() so a starved
            # prefetch thread can drop a pair without flaking the test
            overlap_pairs = [
                t_prep < t_mat
                for t_prep, t_mat in zip(prep_starts[2:], mat_done)]
            try:
                assert overlap_pairs
                assert sum(overlap_pairs) >= 0.7 * len(overlap_pairs), \
                    overlap_pairs
                # the overlap is worth real wall-time: with feed prep
                # (SLEEP) comparable to the step, hiding one behind the
                # other must beat the serial sum by a clear margin
                assert times["pipelined"] < 0.9 * times["sequential"], \
                    times
                return
            except AssertionError as e:
                last_exc = e
        raise last_exc
