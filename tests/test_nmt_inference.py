"""NMT inference + BLEU eval flow (reference: examples/nmt/nmt_test.py
:48-79 testInference, inference_test.py, utils/evaluation_utils.py)."""

import sys

import jax
import numpy as np
import pytest

import parallax_tpu as parallax
from parallax_tpu.common.evaluation import corpus_bleu
from parallax_tpu.models import nmt

sys.path.insert(0, "examples")


class TestCorpusBleu:
    def test_perfect_match_is_100(self):
        refs = [list("abcdefg"), list("hijklmn")]
        assert corpus_bleu(refs, [list(r) for r in refs]) == \
            pytest.approx(100.0)

    def test_empty_hypothesis_is_0(self):
        assert corpus_bleu([list("abcd")], [[]]) == 0.0

    def test_partial_overlap_between_0_and_100(self):
        refs = [list("the cat sat on the mat".split())]
        hyps = [list("the cat sat on a mat".split())]
        b = corpus_bleu(refs, hyps)
        assert 0.0 < b < 100.0

    def test_brevity_penalty_punishes_short_hyps(self):
        ref = [list("abcdefgh")]
        full = corpus_bleu(ref, [list("abcdefgh")])
        short = corpus_bleu(ref, [list("abcd")])
        assert short < full

    def test_known_value(self):
        # one 6-token hyp vs 6-token ref sharing a 5-token prefix:
        # p1=5/6, p2=4/5, p3=3/4, p4=2/3, BP=1 ->
        # 100*exp(mean(log p_n)) = 75.98
        refs = [list("abcdef")]
        hyps = [list("abcdeX")]
        assert corpus_bleu(refs, hyps) == pytest.approx(75.984, abs=0.01)

    def test_mismatched_lengths_raise(self):
        with pytest.raises(ValueError):
            corpus_bleu([list("ab")], [])


def _copy_batches(n_pairs=16, seq=6, vocab=64, seed=0):
    """Fixed copy-task pairs: target = source (the standard seq2seq
    memorization smoke target)."""
    rng = np.random.default_rng(seed)
    src = rng.integers(3, vocab, (n_pairs, seq)).astype(np.int32)
    bos = np.full((n_pairs, 1), nmt.BOS_ID, np.int32)
    eos = np.full((n_pairs, 1), nmt.EOS_ID, np.int32)
    return {
        "src": src,
        "tgt_in": np.concatenate([bos, src], axis=1),
        "tgt_out": np.concatenate([src, eos], axis=1),
    }


def test_untrained_decode_shapes_and_pad_semantics(rng):
    cfg = nmt.tiny_config(vocab_size=64, model_dim=16, num_heads=2,
                          mlp_dim=32, num_layers=1, max_len=8,
                          num_partitions=1)
    params = nmt.build_model(cfg).init_fn(jax.random.PRNGKey(0))
    src = rng.integers(3, 64, (4, 6)).astype(np.int32)
    out_g = np.asarray(nmt.greedy_decode(params, cfg, src))
    out_b = np.asarray(nmt.beam_decode(params, cfg, src, beam_width=3))
    assert out_g.shape == (4, cfg.max_len)
    assert out_b.shape == (4, cfg.max_len)
    for out in (out_g, out_b):
        for row in out:
            eos_pos = np.where(row == nmt.EOS_ID)[0]
            if eos_pos.size:          # after EOS: only PAD
                assert np.all(row[eos_pos[0] + 1:] == nmt.PAD_ID)


@pytest.mark.slow
def test_train_decode_bleu_roundtrip(tmp_path):
    """Train a tiny NMT to memorize copy pairs, checkpoint it, restore
    via the eval flow, greedy- and beam-decode, assert BLEU ~ 100
    (reference nmt_test.py testInference + testTrain in one)."""
    from nmt_eval import decode_and_bleu, restore_params

    ckpt_dir = str(tmp_path / "ckpt")
    cfg = nmt.tiny_config(vocab_size=64, model_dim=32, num_heads=2,
                          mlp_dim=64, num_layers=1, max_len=8,
                          label_smoothing=0.0, learning_rate=3e-3,
                          warmup_steps=30, num_partitions=8)
    batch = _copy_batches()
    sess, *_ = parallax.parallel_run(
        nmt.build_model(cfg),
        parallax_config=parallax.Config(
            run_option="HYBRID", search_partitions=False,
            ckpt_config=parallax.CheckPointConfig(ckpt_dir=ckpt_dir,
                                                  save_ckpt_steps=150)))
    loss = None
    for _ in range(300):
        loss = sess.run("loss", feed_dict=batch)
    sess.close()
    assert loss < 0.15, f"copy task failed to memorize: loss {loss}"

    params, step = restore_params(ckpt_dir, cfg)
    assert step == 300
    pairs = [(batch["src"], batch["tgt_out"])]
    bleu_g, hyps_g = decode_and_bleu(params, cfg, pairs, beam_width=0,
                                     max_len=7)
    bleu_b, hyps_b = decode_and_bleu(params, cfg, pairs, beam_width=4,
                                     max_len=7)
    assert bleu_g > 90.0, (bleu_g, hyps_g[:2])
    assert bleu_b > 90.0, (bleu_b, hyps_b[:2])
    # sanity: the decodes actually reproduce the source tokens
    assert hyps_g[0] == [str(t) for t in batch["src"][0]]


def test_beam_decode_exercises_cached_path_and_matches_cacheless(rng):
    """VERDICT r4 next item 8: beam mode really runs the KV-cached
    incremental step (counted at trace time), and its output equals the
    cache-less reference loop's."""
    from unittest import mock

    cfg = nmt.tiny_config(max_len=24)
    params = nmt.build_model(cfg).init_fn(jax.random.PRNGKey(0))
    import jax.numpy as jnp
    src = jnp.asarray(rng.integers(4, cfg.vocab_size, (3, 10)),
                      jnp.int32)

    real = nmt._decode_step_cached
    calls = {"n": 0}

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    with mock.patch.object(nmt, "_decode_step_cached", counting):
        cached = np.asarray(nmt.beam_decode(params, cfg, src,
                                            beam_width=3,
                                            use_cache=True))
    assert calls["n"] > 0, "beam use_cache=True never hit the cached step"
    cacheless = np.asarray(nmt.beam_decode(params, cfg, src,
                                           beam_width=3,
                                           use_cache=False))
    np.testing.assert_array_equal(cached, cacheless)
