"""Compile-budget guard: one compile per bucket, free steps after.

ISSUE 3 acceptance: a two-bucket warmed-up run must (a) compile each
batch-shape signature exactly once — during warmup, never during the
step loop — and (b) pay no measurable per-step cost for the warmup
dispatch path. Run directly::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/check_compile_budget.py

or via tier-1 (tests/test_compile.py::test_compile_budget_guard).

Methodology (pattern of tools/check_obs_overhead.py):

* **compiles**: ground truth from two independent witnesses — a
  ``jax.monitoring`` listener counting ``backend_compile`` events
  during the ragged step loop (must be 0; warmup owns both compiles),
  and the step jit's own cache size (must stay 0: no step ever took
  the trace-and-compile path, every step dispatched an AOT
  executable). ``engine.recompiles`` must read 0 over the whole ragged
  stream.
* **per-step overhead**: the warmup dispatch path adds exactly three
  host operations to each step — the batch-signature computation, one
  dict lookup, one counter increment. A raw A/B wall-clock diff at
  this scale is pure noise on a shared box (the obs tool's measured
  ±10-20%), so the enforced number decomposes: unit-cost each added
  operation (min over tight batches — minima are robust to
  contention) and divide by the median step wall-time. The raw
  interleaved A/B ratio of AOT-dispatch vs jit-dispatch steps is
  reported (``ab_dispatch_ratio``) for eyeballing, not asserted.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_compile_events = {"n": 0, "active": False}


def _install_listener():
    import jax

    def _listen(event, duration, **kw):
        if _compile_events["active"] and "backend_compile" in event:
            _compile_events["n"] += 1

    jax.monitoring.register_event_duration_secs_listener(_listen)


def _unit_cost_us(fn, iters: int = 2000, batches: int = 7) -> float:
    best = float("inf")
    for _ in range(batches):
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e6


def measure(steps: int = 48, batch: int = 256) -> dict:
    import numpy as np

    import parallax_tpu as parallax
    from parallax_tpu.compile import bucketing
    from parallax_tpu.models import simple

    _install_listener()
    buckets = [batch // 2, batch]
    sess, *_ = parallax.parallel_run(
        simple.build_model(learning_rate=0.1),
        parallax_config=parallax.Config(run_option="AR",
                                        search_partitions=False,
                                        shape_buckets=buckets,
                                        bucket_mask_feed="mask"))
    rng = np.random.default_rng(0)
    # ragged stream over both buckets: full, half, and partial sizes
    sizes = [batch, batch // 2, batch - 8, batch // 2 - 8]
    feeds = [simple.make_batch(rng, s) for s in sizes]
    try:
        warm_stats = sess.warmup(feed_dict=feeds[0])
        n_warmup_compiles = len(warm_stats)

        # -- the guarded loop: zero compiles, all AOT dispatches -------
        hits0 = sess.metrics.counter(
            "engine.executable_cache.hits").value
        _compile_events["n"] = 0
        _compile_events["active"] = True
        times = []
        last = None
        for i in range(steps):
            t0 = time.perf_counter()
            last = sess.run("loss", feed_dict=feeds[i % len(feeds)])
            times.append(time.perf_counter() - t0)
        float(last)  # drain
        _compile_events["active"] = False
        step_us = float(np.median(times)) * 1e6
        loop_compiles = _compile_events["n"]
        jit_cache_size = sess.engine._step_jit._cache_size()
        aot_hits = (sess.metrics.counter(
            "engine.executable_cache.hits").value - hits0)
        recompiles = sess.metrics.counter("engine.recompiles").value

        # -- decomposed per-step cost of the dispatch path -------------
        eng = sess.engine
        placed = eng.shard_batch(feeds[0])
        sig = bucketing.batch_signature(placed)
        sig_us = _unit_cost_us(
            lambda: bucketing.batch_signature(placed), iters=1000)
        lookup_us = _unit_cost_us(lambda: eng._executables.get(sig))
        inc_us = _unit_cost_us(eng._exec_hits.inc)
        # bucketing's full-batch fast path (runs inside shard_batch)
        full = feeds[0]
        bucket_us = _unit_cost_us(
            lambda: bucketing.bucket_batch(full, eng._buckets, "mask"),
            iters=1000)
        added_us = sig_us + lookup_us + inc_us + bucket_us
        overhead_frac = added_us / step_us

        return {
            "n_warmup_compiles": n_warmup_compiles,
            "loop_compiles": loop_compiles,
            "jit_cache_size_after_loop": jit_cache_size,
            "aot_dispatches": aot_hits,
            "steps": steps,
            "recompiles": recompiles,
            "overhead_frac": round(overhead_frac, 5),
            "added_us_per_step": round(added_us, 2),
            "step_us": round(step_us, 1),
            "unit_costs_us": {
                "batch_signature": round(sig_us, 3),
                "executable_lookup": round(lookup_us, 3),
                "counter_inc": round(inc_us, 3),
                "bucket_fast_path": round(bucket_us, 3),
            },
            "warmup_compile_seconds": {str(k): round(v, 3)
                                       for k, v in warm_stats.items()},
        }
    finally:
        sess.close()


def check(result: dict, max_overhead: float = 0.02) -> list:
    """-> list of violated invariants (empty = pass)."""
    bad = []
    if result["n_warmup_compiles"] != 2:
        bad.append(f"warmup compiled {result['n_warmup_compiles']} "
                   f"signatures, expected exactly 2 (one per bucket)")
    if result["loop_compiles"] != 0:
        bad.append(f"{result['loop_compiles']} XLA compile(s) fired "
                   f"during the warmed step loop")
    if result["jit_cache_size_after_loop"] != 0:
        bad.append("a step took the jit trace-and-compile path "
                   "(cache size "
                   f"{result['jit_cache_size_after_loop']} != 0)")
    if result["aot_dispatches"] != result["steps"]:
        bad.append(f"only {result['aot_dispatches']} of "
                   f"{result['steps']} steps dispatched an AOT "
                   f"executable")
    if result["recompiles"] != 0:
        bad.append(f"engine.recompiles = {result['recompiles']} over "
                   f"the ragged stream")
    if result["overhead_frac"] > max_overhead:
        bad.append(f"dispatch-path overhead {result['overhead_frac']} "
                   f"> {max_overhead} of step time")
    return bad


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=48)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--max-overhead", type=float, default=0.02,
                    help="fail when the decomposed per-step dispatch "
                         "cost exceeds this fraction of step wall-time "
                         "(default 0.02 = 2%%)")
    args = ap.parse_args(argv)
    result = measure(steps=args.steps, batch=args.batch)
    violations = check(result, args.max_overhead)
    result["max_overhead"] = args.max_overhead
    result["violations"] = violations
    result["ok"] = not violations
    print(json.dumps(result, indent=2))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
