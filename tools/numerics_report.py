"""Numerics attribution: name the worst layers and the dominant risk.

Reads the per-layer stats trail the numerics observatory
(obs/numwatch.py, ``Config(numerics_interval=N)``) collected and
answers the question a global grad norm cannot: *which layer* is the
one misbehaving and *how* — "layer `decoder` is underflow-bound
(41% of grad entries below bf16 round-off)", not "grad_norm moved".
Each layer is scored against the risk ladder (worst first):

  nonfinite         any non-finite grad entry ever sampled
  unstable_updates  max update ratio ‖Δw‖/‖w‖ above ~0.1 — the weights
                    are moving a double-digit fraction per step
  underflow         max bf16 underflow fraction above ~0.05 — a bf16
                    accumulation would swallow that share of the layer
  vanishing         grad norm collapsed below 1e-9 while the params
                    have not — the layer stopped learning
  healthy

Used three ways:

* ``analyze(trail)`` — pure function over trail snapshots
  (``session.numerics.trail()`` / the ``numerics.trail`` section of a
  flight artifact).
* ``measure()`` — run the simple-model rig with sampling on, report
  its trail analysis, run both kernel drift sentinels clean AND with
  an injected perturbation (the sentinel self-test), and price the
  host-side consume cost — the bench ``numerics`` block.
* CLI::

    JAX_PLATFORMS=cpu python tools/numerics_report.py
    python tools/numerics_report.py --artifact flight_....json

All timings are CPU-relative off-TPU (the drift sentinels run both
executors under Pallas interpret mode — agreement evidence, not TPU
lowering proof), like every kernel number in this repo.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Sequence

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# risk thresholds, in severity order (analyze() walks them top-down)
UPDATE_RATIO_RISK = 0.1
UNDERFLOW_RISK = 0.05
VANISHING_GRAD_NORM = 1e-9

_RISK_ORDER = ("nonfinite", "unstable_updates", "underflow",
               "vanishing", "healthy")


def _layer_summary(layer: str, rows: Sequence[Dict]) -> Dict:
    """Worst-over-trail per-stat summary + risk for one layer."""
    worst = {
        "nonfinite": max(r["nonfinite"] for r in rows),
        "update_ratio": max(r["update_ratio"] for r in rows),
        "underflow_frac": max(r["underflow_frac"] for r in rows),
        "grad_absmax": max(r["grad_absmax"] for r in rows),
    }
    last = rows[-1]
    if worst["nonfinite"] > 0:
        risk = "nonfinite"
        score = 1e9 + worst["nonfinite"]
    elif worst["update_ratio"] > UPDATE_RATIO_RISK:
        risk = "unstable_updates"
        score = 1e6 + worst["update_ratio"]
    elif worst["underflow_frac"] > UNDERFLOW_RISK:
        risk = "underflow"
        score = 1e3 + worst["underflow_frac"]
    elif last["grad_norm"] < VANISHING_GRAD_NORM \
            and last["param_norm"] > 0:
        risk = "vanishing"
        score = 1.0
    else:
        risk = "healthy"
        score = worst["update_ratio"]
    return {
        "layer": layer,
        "risk": risk,
        "score": score,
        "worst": {k: round(float(v), 6) for k, v in worst.items()},
        "last": {k: round(float(v), 6) for k, v in last.items()},
    }


def analyze(trail: Sequence[Dict]) -> Dict:
    """Pure attribution over a stats trail: per-layer risk + the
    dominant (worst) layer. ``trail`` rows are
    ``{"step": int, "stats": {layer: {stat: float}}}`` as produced by
    ``NumericsMonitor.trail()`` and the flight artifact."""
    per_layer: Dict[str, List[Dict]] = {}
    for row in trail or ():
        for layer, stats in (row.get("stats") or {}).items():
            per_layer.setdefault(layer, []).append(stats)
    layers = sorted(
        (_layer_summary(layer, rows)
         for layer, rows in per_layer.items()),
        key=lambda s: (_RISK_ORDER.index(s["risk"]), -s["score"]))
    dominant = layers[0] if layers else None
    return {
        "samples": len(trail or ()),
        "layers": layers,
        "dominant_layer": dominant["layer"] if dominant else None,
        "dominant_risk": dominant["risk"] if dominant else None,
    }


def headline(report: Dict) -> str:
    """One sentence naming the worst layer and its risk."""
    if not report.get("layers"):
        return "numerics: no sampled stats (is numerics_interval set?)"
    dom = report["layers"][0]
    w = dom["worst"]
    detail = {
        "nonfinite": f"{int(w['nonfinite'])} non-finite grad entries",
        "unstable_updates": f"max update ratio {w['update_ratio']:.3g}",
        "underflow": (f"{100 * w['underflow_frac']:.1f}% of grad "
                      f"entries below bf16 round-off"),
        "vanishing": (f"grad norm "
                      f"{dom['last']['grad_norm']:.3g} (stopped "
                      f"learning)"),
        "healthy": f"max update ratio {w['update_ratio']:.3g}",
    }[dom["risk"]]
    return (f"numerics: over {report['samples']} samples, layer "
            f"{dom['layer']!r} is {dom['risk']} ({detail})")


def measure(steps: int = 24, interval: int = 2, batch: int = 64,
            perturb: float = 0.05) -> Dict:
    """Self-contained rig: simple-model session with sampling on →
    trail analysis; both drift sentinels clean AND deliberately
    perturbed (the clean pair must stay silent, the perturbed pair
    must flag — the sentinel self-test bench asserts); host consume
    unit cost. Returns the bench ``numerics`` block."""
    import numpy as np
    import parallax_tpu as parallax
    from parallax_tpu.models import simple
    from parallax_tpu.obs import MetricsRegistry, numwatch

    model = simple.build_model(0.1)
    res = parallax.parallel_run(model, parallax_config=parallax.Config(
        run_option="AR", search_partitions=False,
        numerics_interval=interval))
    sess = res[0] if isinstance(res, tuple) else res
    rng = np.random.default_rng(0)
    try:
        for _ in range(steps):
            sess.run(["loss"], feed_dict={
                "x": rng.standard_normal(batch).astype(np.float32),
                "y": rng.standard_normal(batch).astype(np.float32)})
        sess.numerics.poll(block=True)
        trail = sess.numerics.trail()
        report = analyze(trail)
        samples = sess.numerics.total_samples

        # drift sentinels on live shapes: clean A/B (must stay
        # silent) and a perturbed candidate (must flag)
        drift: Dict[str, Dict] = {}
        clean_silent = True
        for s in numwatch.default_sentinels(sess.metrics):
            r = s.check()
            clean_silent = clean_silent and not r["flagged"]
            drift[r["name"]] = {
                "rel_err": r["rel_err"],
                # ~1.0 clean, moves only on real drift — the
                # regression-gate key (a raw 1e-6 rel_err would
                # ratio-noise between runs)
                "accuracy": r["accuracy"],
                "argmax_flip_frac": r["argmax_flip_frac"],
                "flagged": r["flagged"],
            }
        perturbed_flagged = all(
            s.check()["flagged"]
            for s in numwatch.default_sentinels(perturb=perturb))

        # host-side consume unit cost (the per-sample price
        # check_obs_overhead folds into the obs budget)
        bench_mon = numwatch.NumericsMonitor(MetricsRegistry(),
                                             interval=1)
        fake = {numwatch.SAMPLED_KEY: np.float32(1.0)}
        for layer in ("w", "b"):
            fake[layer] = {s: np.float32(0.1)
                           for s in numwatch.STAT_NAMES}
        t0 = time.perf_counter()
        iters = 2000
        for i in range(iters):
            bench_mon.observe(i, fake)
        consume_us = (time.perf_counter() - t0) / iters * 1e6
    finally:
        sess.close()
    return {
        "steps": steps,
        "interval": interval,
        "samples": samples,
        "consume_us": round(consume_us, 3),
        "report": report,
        "headline": headline(report),
        "drift": drift,
        "drift_clean_silent": clean_silent,
        "drift_perturbed_flagged": perturbed_flagged,
        "cpu_relative": True,  # interpret-mode kernels; not TPU proof
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--artifact", type=str, default=None,
                    help="analyze the numerics.trail section of a "
                         "flight artifact JSON instead of running "
                         "the rig")
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--interval", type=int, default=2)
    args = ap.parse_args(argv)
    if args.artifact:
        with open(args.artifact) as f:
            doc = json.load(f)
        trail = ((doc.get("numerics") or {}).get("trail")
                 or (doc.get("detail") or {}).get("stats_trail") or [])
        report = analyze(trail)
        print(headline(report))
        print(json.dumps(report, indent=1))
        return 0 if report["layers"] else 1
    result = measure(steps=args.steps, interval=args.interval)
    print(result["headline"])
    print(json.dumps(result, indent=1))
    ok = (result["report"]["layers"]
          and result["drift_clean_silent"]
          and result["drift_perturbed_flagged"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
