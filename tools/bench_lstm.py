"""LM1B LSTM training-step fwd+bwd A/B: pallas backward vs recompute.

ISSUE 14 acceptance rig: times the flagship recurrence's forward +
backward under three backends — the VMEM-resident pallas backward
kernel (``bwd_impl='kernel'``), the recompute-XLA VJP it replaced
(``bwd_impl='recompute'``, the r13 behavior and today's refusal
fallback), and the plain XLA scan (``impl='xla'``) — at the op level
(clean signal) AND through one real ``parallel_run`` LM1B training
step (the end-to-end number the headline tracks). The analytic
fwd+bwd HBM-bytes story at the true flagship shape rides along
(``ops/pallas_lstm.kernel_hbm_bytes`` / ``scan_hbm_bytes`` — exact
byte accounting, not a measurement).

HONESTY: on the CPU rig the pallas kernels run in interpret mode, so
the measured ratios price the *interpreter emulation*, not the
TPU memory system the kernel exists for — every ratio is stamped
CPU-relative and the regression gate tracks cross-round DRIFT of this
rig's numbers, never the absolute. The HBM-bytes block is the
hardware claim; the step_ms block is this rig's trajectory.

Keys consumed by bench.py's ``lstm`` block and gated by
tools/check_regression.py: ``op_ms.pallas_bwd`` and
``pallas_over_recompute`` (lower is better for both).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# op-level A/B shape: flagship-proportioned (H = 4E, P = E) but sized
# so the CPU interpreter finishes in seconds; T matches the flagship's
# 20 so the recompute path pays a real T-fold re-walk
OP_SHAPE = dict(T=20, B=32, E=64, H=256, P=64)


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def measure_op(repeats: int = 7, shape=None):
    """Median fwd+bwd wall ms of one op-level training step (loss =
    weighted sum of hs; grads wrt all four params) per backend.

    PARALLAX_LSTM_BWD is snapshotted and CLEARED for the duration:
    the env override outranks the bwd_impl argument, so an ambient
    setting (the documented operational escape hatch) would silently
    collapse every A/B variant onto one backward and feed the drift
    gate a fake ~1.0 ratio."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from parallax_tpu.ops import pallas_lstm

    prior = os.environ.pop("PARALLAX_LSTM_BWD", None)
    try:
        return _measure_op(jax, jnp, np, pallas_lstm, repeats, shape)
    finally:
        if prior is not None:
            os.environ["PARALLAX_LSTM_BWD"] = prior


def _measure_op(jax, jnp, np, pallas_lstm, repeats, shape):

    s = dict(OP_SHAPE, **(shape or {}))
    T, B, E, H, P = s["T"], s["B"], s["E"], s["H"], s["P"]
    rng = np.random.default_rng(0)

    def t(shp, sc=0.2):
        return jnp.asarray(rng.standard_normal(shp) * sc, jnp.float32)
    args = (t((T, B, E)), t((E + P, 4 * H)), t((4 * H,), 0.0),
            t((H, P)))
    g_out = t((T, B, P))

    def grad_fn(impl, **kw):
        # value_and_grad, not grad: a training step consumes the loss,
        # so the forward must stay live — under grad alone XLA DCEs
        # the recompute variant's pallas forward entirely (its
        # recomputed scan IS its forward) and the A/B would compare a
        # bwd-only program against fwd+bwd ones
        return jax.jit(jax.value_and_grad(
            lambda x, w, b, wp: jnp.sum(pallas_lstm.lstm_scan(
                x, w, b, wp, impl=impl, **kw) * g_out),
            argnums=(0, 1, 2, 3)))

    variants = {
        "pallas_bwd": grad_fn("pallas", bwd_impl="kernel"),
        # the shipped default: kernel on TPU, residual-scan executor
        # off-TPU (same algorithm, no interpreter tax, no recompute)
        "auto": grad_fn("pallas", bwd_impl="auto"),
        "recompute": grad_fn("pallas", bwd_impl="recompute"),
        "xla": grad_fn("xla"),
    }

    def timed(fn):
        jax.block_until_ready(fn(*args))               # compile
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            times.append((time.perf_counter() - t0) * 1e3)
        return round(_median(times), 3)

    out = {name: timed(fn) for name, fn in variants.items()}
    # the interpreter-tax witness: forward-only pallas vs forward-only
    # XLA scan at the same shape. Off-TPU the pallas programs run in
    # interpret mode, and this ratio IS that emulation's constant
    # factor — it explains in-artifact why pallas_over_recompute can
    # read > 1 on the CPU rig while the analytic HBM story (the thing
    # the kernel exists for) says < 0.2x on hardware.
    fwd = {
        "pallas": timed(jax.jit(lambda x, w, b, wp:
                                pallas_lstm.lstm_scan(
                                    x, w, b, wp, impl="pallas"))),
        "xla": timed(jax.jit(lambda x, w, b, wp:
                             pallas_lstm.lstm_scan(
                                 x, w, b, wp, impl="xla"))),
    }
    tax = (round(fwd["pallas"] / fwd["xla"], 3) if fwd["xla"]
           else None)
    return out, s, fwd, tax


def measure_train(steps: int = 8, warmup: int = 2):
    """One real LM1B training step (parallel_run, HYBRID, tiny config,
    lstm_impl='pallas') timed with the kernel backward vs the forced
    recompute fallback (PARALLAX_LSTM_BWD env — consulted at trace
    time, so each session re-traces under its own setting)."""
    import jax
    import numpy as np
    import parallax_tpu as parallax
    from parallax_tpu.models import lm1b

    n = jax.device_count()
    out = {}
    prior = os.environ.get("PARALLAX_LSTM_BWD")
    for name, env in (("auto", "auto"), ("pallas_bwd", "kernel"),
                      ("recompute", "recompute")):
        os.environ["PARALLAX_LSTM_BWD"] = env
        try:
            cfg = lm1b.tiny_config(num_partitions=n,
                                   lstm_impl="pallas",
                                   compute_dtype=np.float32)
            sess, *_ = parallax.parallel_run(
                lm1b.build_model(cfg),
                parallax_config=parallax.Config(
                    run_option="HYBRID", search_partitions=False))
            try:
                rng = np.random.default_rng(0)
                batch = lm1b.make_batch(rng, 8 * n, 8, cfg.vocab_size)
                for _ in range(warmup):
                    float(sess.run("loss", feed_dict=batch))
                t0 = time.perf_counter()
                for _ in range(steps):
                    float(sess.run("loss", feed_dict=batch))
                out[name] = round(
                    (time.perf_counter() - t0) / steps * 1e3, 3)
            finally:
                sess.close()
        finally:
            # restore the caller's setting, never just erase it
            if prior is None:
                os.environ.pop("PARALLAX_LSTM_BWD", None)
            else:
                os.environ["PARALLAX_LSTM_BWD"] = prior
    return out


def flagship_hbm_story(n_chips: int = 8):
    """The analytic per-chip fwd+bwd HBM bytes at the TRUE flagship
    (bf16, global B = 128 x chips, T=20) — kernel path vs the XLA
    scan + recompute-VJP alternative. Exact byte accounting from the
    kernel's own block/stream structure; the hardware claim the
    measured CPU ratios cannot make."""
    from parallax_tpu.ops import pallas_lstm

    T, Bc, E, H, P = 20, 128, 512, 2048, 512
    kern = pallas_lstm.kernel_hbm_bytes(T, Bc, E, H, P, 2, 2,
                                        bwd="kernel")
    kern_total = (kern["stream_bytes"]
                  + kern["resident_bytes_per_device"])
    scan_total = pallas_lstm.scan_hbm_bytes(T, Bc, E, H, P, 2, 2,
                                            training=True)
    return {
        "shape": {"T": T, "B_per_chip": Bc, "E": E, "H": H, "P": P,
                  "dtype": "bfloat16", "n_chips": n_chips},
        "kernel_fwd_bwd_bytes_per_chip": kern_total,
        "scan_recompute_bytes_per_chip": scan_total,
        "kernel_over_scan": round(kern_total / scan_total, 4),
        "basis": ("analytic recurrence-traffic accounting (exact for "
                  "the kernel's stream/resident structure); both "
                  "sides exclude the dW-accumulation streams each "
                  "path additionally pays and the hoisted x@w_x both "
                  "share; not a measurement"),
    }


def measure(train: bool = True):
    import jax

    op_ms, shape, fwd_only, tax = measure_op()
    on_cpu = jax.devices()[0].platform == "cpu"
    rec = {
        "platform": jax.devices()[0].platform,
        "op_shape": shape,
        "op_ms": op_ms,
        "pallas_over_recompute": (
            round(op_ms["pallas_bwd"] / op_ms["recompute"], 4)
            if op_ms.get("recompute") else None),
        # the shipped-default backward (kernel on TPU, residual-scan
        # off-TPU) vs the r13 recompute baseline — the rig-honest
        # fwd+bwd win: < 1 means the residual design beats recompute
        # on THIS rig with THIS executor
        "auto_over_recompute": (
            round(op_ms["auto"] / op_ms["recompute"], 4)
            if op_ms.get("recompute") else None),
        "fwd_only_ms": fwd_only,
        "interpret_tax": tax,
        "hbm_bytes_flagship": flagship_hbm_story(jax.device_count()),
        "note": ("CPU rig runs the kernels in interpret mode: the "
                 "measured ratios price the interpreter emulation "
                 "(interpret_tax is the witness — the fwd-only pallas "
                 "vs XLA ratio), NOT the HBM economics the kernel "
                 "exists for; cross-round DRIFT is the gated signal "
                 "and the analytic hbm_bytes_flagship block is the "
                 "hardware claim" if on_cpu
                 else "measured on accelerator"),
    }
    if train:
        try:
            rec["train_step_ms"] = measure_train()
            tr = rec["train_step_ms"]
            if tr.get("recompute"):
                rec["train_pallas_over_recompute"] = round(
                    tr["pallas_bwd"] / tr["recompute"], 4)
                rec["train_auto_over_recompute"] = round(
                    tr["auto"] / tr["recompute"], 4)
        except Exception as e:
            rec["train_step_ms"] = None
            rec["train_error"] = f"{type(e).__name__}: {e}"[:200]
    return rec


if __name__ == "__main__":
    import json
    print(json.dumps(measure(), indent=2))
