"""One MeshSearch decision as a standalone JSON line — the bench
``tune`` block (ISSUE 10).

Run in its OWN process by bench.py: an in-process multi-mesh search is
exactly the workload that intermittently hard-crashes this XLA:CPU
toolchain (see tests/mesh_search_driver.py), and a toolchain abort is
a process kill the worker's try/except can never catch — isolation
makes a crash cost the round its tune block, never the whole BENCH
artifact with the already-measured headline in it.

Always pins itself to the 8-virtual-device CPU platform: on a TPU
round the parent worker holds the chip claim (a second process cannot
initialize it), and a platform-constant block keeps the regression
gate's cross-round ``tune.*`` comparisons apples-to-apples. The
platform is stamped into the block so a reader never mistakes the
predicted-over-measured ratio for a TPU number.

Run: python tools/bench_tune.py
"""

from __future__ import annotations

import json
import os
import sys

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count"
                                 "=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def measure(top_k: int = 3, trial_steps: int = 6,
            trial_warmup: int = 2) -> dict:
    """One tuned smoke-flagship session driven to convergence; returns
    the bench block (tune summary + cache counters, per-plan score
    table dropped — the flight provider keeps it)."""
    import jax
    import numpy as np

    import parallax_tpu as parallax
    from parallax_tpu.models import lm1b

    n_chips = jax.device_count()
    cfg = lm1b.tiny_config(num_partitions=n_chips,
                           num_samples=16 * n_chips)
    sess, *_ = parallax.parallel_run(
        lm1b.build_model(cfg),
        parallax_config=parallax.Config(
            run_option="HYBRID", search_partitions=False,
            tune_config=parallax.TuneConfig(
                top_k=top_k, trial_steps=trial_steps,
                trial_warmup=trial_warmup)))
    try:
        rng = np.random.default_rng(0)
        batch = lm1b.make_batch(rng, 4 * n_chips, 8, cfg.vocab_size)
        for _ in range(top_k * trial_steps + 8):
            sess.run("loss", feed_dict=batch)
            if sess._search is None:
                break
        block = sess.tune_summary()
        if block is None:
            return {"error": "search did not settle"}
        block = dict(block)
        block.pop("scored", None)
        block["engine_cache"] = sess.compile_stats()["engine_cache"]
        w = block.get("winner") or {}
        block["predicted_over_measured"] = \
            w.get("predicted_over_measured")
        block["platform"] = jax.devices()[0].platform
        return block
    finally:
        sess.close()


def measure_pp_trial(top_k: int = 3, trial_steps: int = 4,
                     trial_warmup: int = 1) -> dict:
    """The pipeline-axis companion decision (ISSUE 18): the same
    tuned-session machinery pointed at the tiny pipeline LM with the
    pp dimension open. ``max_tp=1`` keeps the pool to the replicated
    column, so beyond the one 2-D plan every candidate is a genuine
    ``pp > 1`` plan and the shortlist must trial at least one. The
    gated number is a pp>1 trial row's predicted-over-measured —
    CPU-relative in absolute terms; cross-round DRIFT is the signal
    (the bubble+transfer pricing and the measured schedule coming
    apart)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import parallax_tpu as parallax
    from parallax_tpu.models import long_context as lc

    n_chips = jax.device_count()
    cfg = lc.tiny_config(parallelism="pipeline", num_layers=8,
                         num_microbatches=4,
                         compute_dtype=jnp.float32)
    sess, *_ = parallax.parallel_run(
        lc.build_model(cfg),
        parallax_config=parallax.Config(
            run_option="HYBRID", search_partitions=False,
            tune_config=parallax.TuneConfig(
                top_k=top_k, trial_steps=trial_steps,
                trial_warmup=trial_warmup,
                run_options=("HYBRID",), max_tp=1,
                max_pp=n_chips)),
        num_partitions=1)
    try:
        batch = lc.make_batch(np.random.default_rng(0), 32, 16,
                              cfg.vocab_size)
        for _ in range(top_k * trial_steps + 8):
            sess.run("loss", feed_dict=batch)
            if sess._search is None:
                break
        block = sess.tune_summary()
        if block is None:
            return {"error": "pp search did not settle"}
        rows = [t for t in (block.get("trials") or [])
                if "xpp" in t["plan"] and t.get("measured_ms")
                and t.get("predicted_ms")]
        if not rows:
            return {"error": "no pp > 1 plan reached a measured trial"}
        row = rows[0]
        w = block.get("winner") or {}
        return {
            "plan": row["plan"],
            "predicted_ms": row["predicted_ms"],
            "measured_ms": row["measured_ms"],
            "predicted_over_measured": round(
                row["predicted_ms"] / row["measured_ms"], 6),
            "winner_plan": w.get("plan"),
            "winner_pp": w.get("pp"),
            "winner_bubble_fraction": w.get("bubble_fraction"),
        }
    finally:
        sess.close()


def main():
    block = measure()
    try:
        block["pp_trial"] = measure_pp_trial()
    except Exception as exc:  # a pp failure costs only the sub-block
        block["pp_trial"] = {"error": repr(exc)}
    print(json.dumps(block))


if __name__ == "__main__":
    main()
