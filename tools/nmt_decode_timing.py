"""KV-cache decode evidence (VERDICT r4 next item 8): time the cached
vs cache-less NMT greedy decode at several target lengths and write
``perf/NMT_DECODE_r05.json``.

The cache-less loop re-runs the causal decoder over the whole [T]
buffer per emitted token (O(T^2) total attention work); the cached path
(models/nmt.py:226-289) computes each new token against per-layer K/V
caches (O(T) total). Reference analogue:
``/root/reference/parallax/parallax/examples/nmt/inference.py`` decodes
through tf.while_loop with the attention wrapper's state — the cached
formulation. CPU timings (compile excluded) are structure, not
hardware: the ratio's growth with T is the O(T) vs O(T^2) signature.

``measure()`` is also stamped into the BENCH JSON as the ``decode``
block (bench.py), so the serve-side latency primitive gets a per-round
trajectory instead of this one-off perf file.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def measure(lengths=(32, 64, 128), batch=4, repeats=3) -> dict:
    """Cached-vs-cacheless greedy decode wall times; JSON-ready."""
    import jax
    import numpy as np

    from parallax_tpu.models import nmt

    cfg = nmt.tiny_config(max_len=max(lengths))
    params = nmt.build_model(cfg).init_fn(jax.random.PRNGKey(0))
    # params come from Model.init_fn as host arrays; decode fns jit
    rng = np.random.default_rng(0)
    src = rng.integers(4, cfg.vocab_size, (batch, 16)).astype(np.int32)

    rows = []
    for T in lengths:
        entry = {"target_len": int(T), "batch": batch}
        for use_cache, key in ((True, "cached_ms"), (False, "cacheless_ms")):
            fn = jax.jit(lambda p, s: nmt.greedy_decode(
                p, cfg, s, max_len=T, use_cache=use_cache))
            out = fn(params, src)               # compile + warm
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(repeats):
                jax.block_until_ready(fn(params, src))
            entry[key] = round((time.perf_counter() - t0) / repeats
                               * 1000, 2)
        entry["cacheless_over_cached"] = round(
            entry["cacheless_ms"] / entry["cached_ms"], 2)
        rows.append(entry)
        # '#'-prefixed: bench.py calls measure() inline and its stdout
        # contract is diagnostics behind '#' + ONE final JSON line
        print(f"# {entry}", flush=True)

    ratios = [r["cacheless_over_cached"] for r in rows]
    return {
        "what": "NMT greedy decode wall time, cached (O(T)) vs "
                "cache-less (O(T^2)) — models/nmt.py",
        "platform": jax.devices()[0].platform,
        "model": "nmt.tiny_config",
        "rows": rows,
        # the O(T) vs O(T^2) signature: the advantage grows with T
        "ratio_grows_with_T": bool(all(
            b >= a for a, b in zip(ratios, ratios[1:]))),
    }


def main(lengths=(32, 64, 128), batch=4, repeats=3):
    result = measure(lengths=lengths, batch=batch, repeats=repeats)
    out_path = os.path.join(os.path.dirname(__file__), "..", "perf",
                            "NMT_DECODE_r05.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
