"""KV-cache decode evidence: cached-vs-cacheless, paged-vs-dense and
speculative-vs-plain A/Bs at fixed shapes, written to
``perf/NMT_DECODE_r06.json`` and stamped into the BENCH ``decode``
block (bench.py) so every serve-side latency primitive has a per-round
trajectory.

* **cached vs cache-less** (PR 4): the O(T) vs O(T^2) signature — the
  ratio grows with target length.
* **paged vs dense** (ISSUE 6): the same per-slot-position decode step
  against the dense ``[L, S, T, D]`` cache vs the gather-based
  ``[L, pool, page, D]`` pool at identical shapes. CPU wall-clock
  prices the gather/scatter overhead; the paged win is MEMORY — the
  report also states the KV bytes each layout needs for the same slot
  count, which is the concurrency headroom the serve sweep
  (tools/loadgen.py --sweep) converts into tokens/sec.
* **speculative vs plain** (ISSUE 6): tokens/sec of the plain
  one-token step loop vs the draft-propose/verify loop with a
  layer-skip draft, acceptance rate recorded; plus the perfect-draft
  (draft == target) ceiling that bounds what a TRAINED draft could
  buy. Random weights give a low real acceptance — the ratio is
  reported with its acceptance so the number explains itself.

CPU timings (compile excluded) are structure, not hardware.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _cached_vs_cacheless(lengths, batch, repeats) -> dict:
    import jax
    import numpy as np

    from parallax_tpu.models import nmt

    cfg = nmt.tiny_config(max_len=max(lengths))
    params = nmt.build_model(cfg).init_fn(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    src = rng.integers(4, cfg.vocab_size, (batch, 16)).astype(np.int32)

    rows = []
    for T in lengths:
        entry = {"target_len": int(T), "batch": batch}
        for use_cache, key in ((True, "cached_ms"), (False, "cacheless_ms")):
            fn = jax.jit(lambda p, s: nmt.greedy_decode(
                p, cfg, s, max_len=T, use_cache=use_cache))
            out = fn(params, src)               # compile + warm
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(repeats):
                jax.block_until_ready(fn(params, src))
            entry[key] = round((time.perf_counter() - t0) / repeats
                               * 1000, 2)
        entry["cacheless_over_cached"] = round(
            entry["cacheless_ms"] / entry["cached_ms"], 2)
        rows.append(entry)
        # '#'-prefixed: bench.py calls measure() inline and its stdout
        # contract is diagnostics behind '#' + ONE final JSON line
        print(f"# {entry}", flush=True)
    return {"rows": rows,
            "ratio_grows_with_T": bool(all(
                b >= a for a, b in zip(
                    (r["cacheless_over_cached"] for r in rows),
                    [r["cacheless_over_cached"] for r in rows][1:])))}


def _decode_rig(slots, T, Ts, model_dim=64, num_layers=2, **prog_kw):
    """A program + state with every slot prefilled — the step-loop
    rig shared by the paged and speculative A/Bs."""
    import jax
    import numpy as np

    from parallax_tpu.models import nmt
    from parallax_tpu.serve.adapters import NMTDecodeProgram

    cfg = nmt.tiny_config(vocab_size=256, model_dim=model_dim,
                          num_heads=4, mlp_dim=2 * model_dim,
                          num_layers=num_layers, max_len=max(T, Ts),
                          num_partitions=1)
    params = nmt.build_model(cfg).init_fn(jax.random.PRNGKey(0))
    if prog_kw.pop("layer_skip_draft", False):
        from parallax_tpu.serve.adapters import layer_skip_draft
        dcfg, dparams = layer_skip_draft(cfg, params)
        prog_kw.update(draft_cfg=dcfg, draft_params=dparams)
    elif prog_kw.pop("perfect_draft", False):
        prog_kw.update(draft_cfg=cfg, draft_params=params)
    prog = NMTDecodeProgram(cfg, max_src_len=Ts, max_len=T, **prog_kw)
    state = prog.init_state(params, slots)
    rng = np.random.default_rng(3)
    for j in range(slots):
        feed = prog.prepare_feed(
            {"src": rng.integers(3, 256, (Ts,)).astype(np.int32)})
        rs = prog.prefill(params, feed)
        state = prog.insert(state, np.int32(j), rs)
    return prog, params, state, cfg


def _paged_vs_dense(slots=8, T=32, page_size=8, steps=24) -> dict:
    import jax
    import numpy as np

    def time_steps(prog, params, state, pages):
        tok = np.full((slots,), prog.bos_id, np.int32)
        t = np.zeros((slots,), np.int32)
        # warm
        if pages is None:
            nxt, state = prog.step(params, state, tok, t)
        else:
            nxt, state = prog.step(params, state, tok, t, pages)
        jax.block_until_ready(nxt)
        t0 = time.perf_counter()
        for i in range(steps):
            ti = np.full((slots,), i, np.int32)
            if pages is None:
                nxt, state = prog.step(params, state, tok, ti)
            else:
                nxt, state = prog.step(params, state, tok, ti, pages)
            tok = np.asarray(nxt)
        jax.block_until_ready(nxt)
        return (time.perf_counter() - t0) / steps * 1e3

    dense_prog, dp, ds, cfg = _decode_rig(slots, T, 16)
    dense_ms = time_steps(dense_prog, dp, ds, None)
    pool = slots * (T // page_size)
    paged_prog, pp, ps_state, _ = _decode_rig(
        slots, T, 16, page_size=page_size, pool_pages=pool)
    pages = np.arange(pool, dtype=np.int32).reshape(
        slots, T // page_size)
    paged_ms = time_steps(paged_prog, pp, ps_state, pages)
    import jax.numpy as jnp
    itemsize = jnp.zeros((), cfg.compute_dtype).dtype.itemsize
    # k+v bytes per cached position, in the model's compute dtype
    bytes_per = 2 * cfg.num_layers * cfg.model_dim * itemsize
    out = {
        "slots": slots, "target_len": T, "page_size": page_size,
        "pool_pages": pool, "steps": steps,
        "dense_step_ms": round(dense_ms, 3),
        "paged_step_ms": round(paged_ms, 3),
        "paged_over_dense": round(paged_ms / dense_ms, 3),
        # the memory story: dense pays slots*T positions up front,
        # paged pays only in-flight pages — with short/mixed caps the
        # pool serves the same slots in a fraction of the bytes, or
        # 8-64x the slots in the same bytes (the sweep measures that)
        "kv_bytes_dense": slots * T * bytes_per,
        "kv_bytes_paged_pool": pool * page_size * bytes_per,
        "note": ("CPU step wall prices the gather/scatter overhead; "
                 "the paged win is concurrency per byte, measured by "
                 "the serve.continuous sweep"),
    }
    print(f"# paged_vs_dense {out}", flush=True)
    return out


def _spec_vs_plain(slots=8, T=32, draft="layer_skip",
                   model_dim=128, num_layers=4) -> dict:
    """Tokens/sec of the plain step loop vs the speculative loop over
    the same decode window (emulates the scheduler's accept/rollback
    host loop without the queue). The rig is deliberately
    compute-dominated (4 target layers vs a 1-layer draft) so the A/B
    prices the draft/verify economics, not CPU dispatch overhead."""
    import jax
    import numpy as np

    k = 3
    plain_prog, pp, plain_state, _ = _decode_rig(
        slots, T, 16, model_dim=model_dim, num_layers=num_layers)
    tok = np.full((slots,), plain_prog.bos_id, np.int32)
    t = np.zeros((slots,), np.int32)
    nxt, plain_state = plain_prog.step(pp, plain_state, tok, t)
    jax.block_until_ready(nxt)  # warm
    n_steps = T - 1
    t0 = time.perf_counter()
    for i in range(n_steps):
        ti = np.full((slots,), i, np.int32)
        nxt, plain_state = plain_prog.step(pp, plain_state, tok, ti)
        tok = np.asarray(nxt)
    plain_wall = time.perf_counter() - t0
    plain_tps = slots * n_steps / plain_wall

    kw = ({"layer_skip_draft": True} if draft == "layer_skip"
          else {"perfect_draft": True})
    spec_prog, sp, spec_state, _ = _decode_rig(
        slots, T, 16, model_dim=model_dim, num_layers=num_layers,
        spec_tokens=k, **kw)
    tok = np.full((slots,), spec_prog.bos_id, np.int32)
    prev = tok.copy()
    t = np.zeros((slots,), np.int32)
    y, props, spec_state = spec_prog.spec_step(sp, spec_state, tok, t,
                                               prev)
    jax.block_until_ready(y)  # warm
    emitted = 0
    proposed = 0
    accepted = 0
    t0 = time.perf_counter()
    iters = 0
    while int(t.min()) < T - k - 1:
        y, props, spec_state = spec_prog.spec_step(sp, spec_state, tok,
                                                   t, prev)
        y = np.asarray(y)
        props = np.asarray(props)
        iters += 1
        for j in range(slots):
            n = 1
            while n <= k and props[j, n - 1] == y[j, n - 1]:
                n += 1
            proposed += k
            accepted += n - 1
            emitted += n
            prev[j] = y[j, n - 2] if n >= 2 else tok[j]
            tok[j] = y[j, n - 1]
            t[j] += n
    spec_wall = time.perf_counter() - t0
    spec_tps = emitted / spec_wall if spec_wall > 0 else None
    # the economics the measured ratio decomposes into: one spec
    # iteration costs iter_ms and emits (1 + k*accept) tokens/slot on
    # average, so spec beats plain exactly when acceptance clears the
    # breakeven — random weights sit far below it, a TRAINED draft's
    # typical 0.6-0.9 sits above when the draft is cheap enough
    step_ms = plain_wall / n_steps * 1e3
    iter_ms = spec_wall / iters * 1e3 if iters else None
    cost_ratio = iter_ms / step_ms if iter_ms else None
    breakeven = (max(0.0, (cost_ratio - 1.0) / k)
                 if cost_ratio is not None else None)

    def _proj(a):
        return (round((1 + k * a) / cost_ratio, 3)
                if cost_ratio else None)

    out = {
        "slots": slots, "target_len": T, "spec_tokens": k,
        "draft": draft,
        "accept_rate": round(accepted / proposed, 4) if proposed else None,
        "tokens_per_sec_plain": round(plain_tps, 1),
        "tokens_per_sec_spec": round(spec_tps, 1) if spec_tps else None,
        "spec_over_plain": (round(spec_tps / plain_tps, 3)
                            if spec_tps else None),
        "iterations": iters,
        "step_ms_plain": round(step_ms, 3),
        "iter_ms_spec": round(iter_ms, 3) if iter_ms else None,
        "iter_over_step_cost": (round(cost_ratio, 3)
                                if cost_ratio else None),
        "breakeven_accept_rate": (round(breakeven, 3)
                                  if breakeven is not None else None),
        "projected_speedup_at_accept": {"0.6": _proj(0.6),
                                        "0.8": _proj(0.8),
                                        "1.0": _proj(1.0)},
    }
    print(f"# spec_vs_plain {out}", flush=True)
    return out


def measure(lengths=(32, 64, 128), batch=4, repeats=3,
            ab: bool = True) -> dict:
    """Cached-vs-cacheless greedy decode wall times plus the ISSUE 6
    paged/speculative A/Bs; JSON-ready."""
    import jax

    base = _cached_vs_cacheless(lengths, batch, repeats)
    result = {
        "what": "NMT decode wall time: cached (O(T)) vs cache-less "
                "(O(T^2)); paged-vs-dense and speculative-vs-plain "
                "A/Bs at fixed shapes — models/nmt.py + "
                "serve/adapters.py",
        "platform": jax.devices()[0].platform,
        "model": "nmt.tiny_config",
        "rows": base["rows"],
        # the O(T) vs O(T^2) signature: the advantage grows with T
        "ratio_grows_with_T": base["ratio_grows_with_T"],
    }
    if ab:
        result["paged_vs_dense"] = _paged_vs_dense()
        result["spec_vs_plain"] = _spec_vs_plain(draft="layer_skip")
        result["spec_ceiling"] = _spec_vs_plain(draft="perfect")
    return result


def main(lengths=(32, 64, 128), batch=4, repeats=3):
    result = measure(lengths=lengths, batch=batch, repeats=repeats)
    out_path = os.path.join(os.path.dirname(__file__), "..", "perf",
                            "NMT_DECODE_r06.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
