"""Paged-attention decode-step A/B: Pallas kernel vs einsum gather.

ISSUE 16 acceptance rig: times one paged self-attention decode step
(``ops/pallas_paged_attention.paged_decode_attention``) under both
executors — ``impl='kernel'`` (stream only live pages through VMEM)
and ``impl='einsum'`` (the full-width clip-then-mask gather the kernel
replaces) — at several pool occupancies. The kernel's claim is
occupancy-PROPORTIONAL traffic, so the A/B is run at 25%, 50% and
100% live pages; the einsum path's cost is occupancy-flat by
construction. The analytic HBM table at the true flagship decode
shape rides along (``kernel_hbm_bytes`` / ``gather_hbm_bytes`` —
exact byte accounting, not a measurement).

HONESTY: on the CPU rig the kernel runs in interpret mode, so the
measured ratios price the *interpreter emulation*, not the TPU memory
system the kernel exists for — every ratio is stamped CPU-relative
and the regression gate tracks cross-round DRIFT, never the absolute.
``interpret_tax`` is the in-artifact witness: the kernel/einsum ratio
at 100% occupancy, where BOTH paths touch the same KV bytes on
hardware — the residual gap there IS the emulation constant, which
explains why ``kernel_over_einsum`` can read > 1 on this rig while
the analytic table (the hardware claim) scales with occupancy.

Keys consumed by bench.py's ``attn`` block and gated by
tools/check_regression.py: ``step_ms.kernel`` (lower is better) and
``kernel_over_einsum`` (two-sided drift — measured at 50% occupancy,
the sparse regime the kernel exists for).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# op-level A/B shape: flagship-proportioned (G = spec verify width 3,
# 8-token pages, table width 8) but sized so the CPU interpreter
# finishes in seconds; pool is ~2.5x one batch's table footprint so
# live pages scatter non-contiguously like a real pool
OP_SHAPE = dict(S=8, G=3, D=128, num_heads=4, page_size=16, P=8,
                pool_pages=160)

OCCUPANCIES = (0.25, 0.5, 1.0)


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def measure_op(repeats: int = 7, shape=None):
    """Median wall ms of one paged decode-step attention per executor
    per occupancy.

    PARALLAX_PAGED_ATTN is snapshotted and CLEARED for the duration:
    the env override outranks the impl argument, so an ambient setting
    (the documented operational escape hatch) would silently collapse
    both A/B arms onto one executor and feed the drift gate a fake
    ~1.0 ratio."""
    prior = os.environ.pop("PARALLAX_PAGED_ATTN", None)
    try:
        return _measure_op(repeats, shape)
    finally:
        if prior is not None:
            os.environ["PARALLAX_PAGED_ATTN"] = prior


def _measure_op(repeats, shape):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from parallax_tpu.ops import pallas_paged_attention as ppa

    s = dict(OP_SHAPE, **(shape or {}))
    S, G, D = s["S"], s["G"], s["D"]
    H, ps, P, pool = (s["num_heads"], s["page_size"], s["P"],
                      s["pool_pages"])
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((S, G, D)) * 0.2, jnp.float32)
    kp = jnp.asarray(rng.standard_normal((pool, ps, D)) * 0.2,
                     jnp.float32)
    vp = jnp.asarray(rng.standard_normal((pool, ps, D)) * 0.2,
                     jnp.float32)

    def rig(occ):
        n_live = max(1, int(round(occ * P)))
        pages = np.full((S, P), pool, np.int32)
        for i in range(S):
            pages[i, :n_live] = rng.choice(pool, n_live, replace=False)
        pos = np.full((S, G), n_live * ps - 1, np.int32)
        return jnp.asarray(pages), jnp.asarray(pos)

    def timed(fn, *args):
        jax.block_until_ready(fn(*args))               # compile
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            times.append((time.perf_counter() - t0) * 1e3)
        return round(_median(times), 3)

    def step_fn(impl):
        return jax.jit(lambda q, kp, vp, pages, pos:
                       ppa.paged_decode_attention(
                           q, kp, vp, pages, pos, num_heads=H,
                           page_size=ps, impl=impl))

    sweep = {}
    for occ in OCCUPANCIES:
        pages, pos = rig(occ)
        sweep[str(occ)] = {
            "kernel": timed(step_fn("kernel"), q, kp, vp, pages, pos),
            "einsum": timed(step_fn("einsum"), q, kp, vp, pages, pos),
        }
    return sweep, s


def flagship_hbm_story():
    """The analytic per-decode-step HBM bytes at the TRUE flagship
    decode shape (bf16, ops/pallas_paged_attention.FLAGSHIP_DECODE)
    across occupancies — live-pages-only kernel stream vs the
    occupancy-flat full-width gather. Exact byte accounting from the
    kernel's block/stream structure; the hardware claim the measured
    CPU ratios cannot make."""
    from parallax_tpu.ops import pallas_paged_attention as ppa

    F = ppa.FLAGSHIP_DECODE
    S, G, D = F["S"], F["G"], F["D"]
    ps, P = F["page_size"], F["P"]
    gather = ppa.gather_hbm_bytes(S, G, D, ps, P, 2)["total_bytes"]
    rows = {}
    for occ in OCCUPANCIES:
        live = int(round(occ * S * P))
        kern = ppa.kernel_hbm_bytes(S, G, D, ps, live,
                                    2)["total_bytes"]
        rows[str(occ)] = {
            "kernel_bytes": kern,
            "gather_bytes": gather,
            "kernel_over_gather": round(kern / gather, 4),
        }
    return {
        "shape": dict(F, dtype="bfloat16"),
        "per_step": rows,
        "basis": ("analytic page-stream accounting (exact for the "
                  "kernel's one-block-per-live-page structure; the "
                  "gather side counts the pool read, the materialized "
                  "K/V view write and the attention re-read); both "
                  "sides exclude the q/k/v projections and output "
                  "matmul each path equally pays; not a measurement"),
    }


def measure():
    import jax

    sweep, shape = measure_op()
    on_cpu = jax.devices()[0].platform == "cpu"
    mid = sweep[str(0.5)]
    full = sweep[str(1.0)]
    return {
        "platform": jax.devices()[0].platform,
        "op_shape": shape,
        "occupancy_sweep_ms": sweep,
        # the gated pair, at the sparse occupancy the kernel exists
        # for (50% live pages)
        "step_ms": {"kernel": mid["kernel"], "einsum": mid["einsum"]},
        "kernel_over_einsum": (
            round(mid["kernel"] / mid["einsum"], 4)
            if mid["einsum"] else None),
        # equal-bytes witness: at 100% occupancy both executors touch
        # the same KV bytes on hardware, so this ratio is the
        # interpreter emulation constant on the CPU rig
        "interpret_tax": (
            round(full["kernel"] / full["einsum"], 4)
            if full["einsum"] else None),
        "hbm_bytes_flagship": flagship_hbm_story(),
        "note": ("CPU rig runs the kernel in interpret mode: the "
                 "measured ratios price the interpreter emulation "
                 "(interpret_tax is the witness — the kernel/einsum "
                 "ratio at equal-bytes 100% occupancy), NOT the HBM "
                 "economics the kernel exists for; cross-round DRIFT "
                 "is the gated signal and the analytic "
                 "hbm_bytes_flagship block is the hardware claim"
                 if on_cpu else "measured on accelerator"),
    }


if __name__ == "__main__":
    import json
    print(json.dumps(measure(), indent=2))
