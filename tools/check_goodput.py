"""Goodput-ledger chaos guard: the run account must survive contact
with failure — gated.

ISSUE 20 acceptance, enforced in tier-1
(tests/test_ops.py::test_goodput_chaos_guard via the established
subprocess-driver pattern) and runnable directly::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/check_goodput.py

Three phases over the deterministic simple-model loop (same rig as
tools/check_train_faults.py):

* **clean** — N uninterrupted steps. The ledger's account must sum to
  its wall EXACTLY (``unattributed`` is the constructed remainder),
  the ledger wall must agree with the parent-measured wall (child
  spawn epoch -> child end stamp) within 5% (the
  ``PARALLAX_RUN_EPOCH`` anchor working), and the built-in alert
  rules must fire ZERO alerts on a healthy run.
* **sigkill-resume** — checkpoints every k steps, SIGKILL mid-run,
  relaunch. The resumed ledger (restored through the checkpoint
  manifest extras) must span BOTH attempts: ``attempts == 2``,
  ``restore_replay > 0`` (the restore-verify wall), and
  ``eviction_downtime > 0`` (save -> respawn dead air, which includes
  the lost unsaved tail); its cumulative wall must agree with the
  parent's two-spawn measurement within 5%.
* **nan-rollback** — one poisoned batch under auto-recovery: the
  discarded steps' measured time must land in ``rollback_discarded``
  (> 0), and the journal must carry the
  ``recovery/nonfinite_rollback`` and ``ops/rollback_discarded``
  events in causal order.

All numbers are CPU-relative until the TPU relay appears.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

STEPS = 12
CKPT_EVERY = 4
WALL_TOLERANCE = 0.05  # ledger wall vs parent-measured wall


# ---------------------------------------------------------------------------
# child: one deterministic training run, account written at exit
# ---------------------------------------------------------------------------

def _batch_for(i: int, nan: bool = False):
    import numpy as np
    from parallax_tpu.models import simple
    b = simple.make_batch(np.random.default_rng(1000 + i), 32)
    if nan:
        b["x"] = b["x"] * np.nan
    return b


def child_main(args) -> int:
    import parallax_tpu as parallax
    from parallax_tpu.models import simple

    nan_at = {int(s) for s in args.nan_at.split(",") if s}
    cfg = parallax.Config(
        run_option="AR", search_partitions=False,
        flight_dir=args.flight_dir or None,
        journal_path=args.journal or None,
        ckpt_config=parallax.CheckPointConfig(
            ckpt_dir=args.ckpt_dir or None,
            save_ckpt_steps=CKPT_EVERY if args.ckpt_dir else None),
        recovery_config=parallax.RecoveryConfig(
            enabled=bool(args.recovery), snapshot_every_steps=2,
            max_retries=2))
    sess, *_ = parallax.parallel_run(simple.build_model(0.1),
                                     parallax_config=cfg)
    sess.prepare(_batch_for(0))
    i = sess.data_cursor
    while i < args.steps:
        sess.run("loss", feed_dict=_batch_for(i, nan=i in nan_at))
        if args.crash_at >= 0 and i + 1 >= args.crash_at:
            os.kill(os.getpid(), signal.SIGKILL)  # no cleanup, ever
        i += 1
    # the account as of run end: the parent joins this end stamp with
    # the spawn epoch it injected to measure the true wall
    doc = {
        "account": sess.ops_account(),
        "alerts": (sess.alerts.summary()
                   if sess.alerts is not None else None),
        "journal_events": (sess.journal.seq
                           if sess.journal is not None else 0),
        "t_end": time.time(),
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, default=str)
    sess.close()
    return 0


# ---------------------------------------------------------------------------
# parent: orchestrate the phases
# ---------------------------------------------------------------------------

def _run_child(out, ckpt_dir="", flight_dir="", journal="",
               crash_at=-1, nan_at="", recovery=False, env=None,
               timeout=300.0, steps=STEPS):
    """Spawn one training child; stamps PARALLAX_RUN_EPOCH at spawn
    (what the launcher does for real workers) and returns
    ``(proc, spawn_epoch)``."""
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--out", out, "--ckpt-dir", ckpt_dir,
           "--flight-dir", flight_dir, "--journal", journal,
           "--steps", str(steps), "--crash-at", str(crash_at),
           "--nan-at", nan_at]
    if recovery:
        cmd.append("--recovery")
    spawn_epoch = time.time()
    full_env = dict(os.environ, JAX_PLATFORMS="cpu",
                    PARALLAX_RUN_EPOCH=f"{spawn_epoch:.6f}")
    full_env.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    full_env.update(env or {})
    return subprocess.run(cmd, env=full_env, timeout=timeout,
                          capture_output=True, text=True), spawn_epoch


def _read_doc(path) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _sum_check(acct) -> dict:
    """The by-construction invariant plus the inner-class view."""
    badput = acct.get("badput_s") or {}
    total = acct.get("productive_s", 0.0) + sum(badput.values())
    return {
        "wall_s": acct.get("wall_s"),
        "accounted_s": round(total, 6),
        "exact": abs(total - acct.get("wall_s", 0.0)) < 1e-4,
    }


def measure(steps: int = STEPS) -> dict:
    result: dict = {"steps": steps, "ckpt_every": CKPT_EVERY,
                    "tolerance": WALL_TOLERANCE}
    work = tempfile.mkdtemp(prefix="goodput_guard_")

    # -- phase 1: clean run — sums to wall, zero alerts ----------------
    out1 = os.path.join(work, "clean.json")
    j1 = os.path.join(work, "clean_journal.jsonl")
    p1, epoch1 = _run_child(out1, journal=j1, steps=steps)
    d1 = _read_doc(out1)
    a1 = d1.get("account") or {}
    from parallax_tpu.obs.journal import read_journal
    evs1 = read_journal(j1)  # read after exit: close() journals last
    parent_wall = (d1.get("t_end", 0.0) - epoch1) or None
    result["clean"] = {
        "rc": p1.returncode,
        "sum": _sum_check(a1),
        "parent_wall_s": round(parent_wall, 3) if parent_wall else None,
        "ledger_wall_s": a1.get("wall_s"),
        "wall_rel_err": (round(abs(a1.get("wall_s", 0.0) - parent_wall)
                               / parent_wall, 4)
                         if parent_wall else None),
        "goodput_fraction": a1.get("goodput_fraction"),
        "attempts": a1.get("attempts"),
        "alerts_fired": ((d1.get("alerts") or {}).get("firings_total")
                         if d1.get("alerts") else None),
        "journal_events": len(evs1),
    }

    # -- phase 2: SIGKILL mid-run, ledger spans both attempts ----------
    ck2 = os.path.join(work, "ck_sigkill")
    out2 = os.path.join(work, "sigkill.json")
    j2 = os.path.join(work, "sigkill_journal.jsonl")
    crash_at = CKPT_EVERY * 2 + 1  # past the 2nd checkpoint commit
    p2a, epoch2a = _run_child(out2, ckpt_dir=ck2, journal=j2,
                              crash_at=crash_at, steps=steps)
    p2b, _ = _run_child(out2, ckpt_dir=ck2, journal=j2, steps=steps)
    d2 = _read_doc(out2)
    a2 = d2.get("account") or {}
    badput2 = a2.get("badput_s") or {}
    # the TRUE wall of the whole run: first spawn -> resumed child's
    # end stamp (one wall-clock domain; both stamps are time.time())
    parent_wall2 = (d2.get("t_end", 0.0) - epoch2a) or None
    result["sigkill"] = {
        "crash_rc": p2a.returncode,
        "resume_rc": p2b.returncode,
        "sum": _sum_check(a2),
        "attempts": a2.get("attempts"),
        "parent_wall_s": (round(parent_wall2, 3)
                          if parent_wall2 else None),
        "ledger_wall_s": a2.get("wall_s"),
        "wall_rel_err": (round(abs(a2.get("wall_s", 0.0)
                                   - parent_wall2) / parent_wall2, 4)
                         if parent_wall2 else None),
        "restore_replay_s": badput2.get("restore_replay"),
        "eviction_downtime_s": badput2.get("eviction_downtime"),
        "steps_recorded": a2.get("steps"),
    }

    # -- phase 3: NaN rollback — discarded work in its own class -------
    fl3 = os.path.join(work, "fl_nan")
    out3 = os.path.join(work, "nan.json")
    j3 = os.path.join(work, "nan_journal.jsonl")
    p3, _ = _run_child(out3, flight_dir=fl3, journal=j3, nan_at="6",
                       recovery=True, steps=steps)
    d3 = _read_doc(out3)
    a3 = d3.get("account") or {}
    evs = read_journal(j3)
    kinds = [(e.get("subsystem"), e.get("kind")) for e in evs]
    result["nan"] = {
        "rc": p3.returncode,
        "sum": _sum_check(a3),
        "rollback_discarded_s": (a3.get("badput_s")
                                 or {}).get("rollback_discarded"),
        "journal_kinds": sorted(set(kinds)),
        "rollback_before_discard": _in_order(
            kinds, ("recovery", "nonfinite_rollback"),
            ("ops", "rollback_discarded")),
    }

    result["bench"] = {
        "steps": steps,
        "clean_goodput_fraction": result["clean"]["goodput_fraction"],
        "clean_badput_s": a1.get("badput_s"),
        "clean_wall_rel_err": result["clean"]["wall_rel_err"],
        "resume_wall_rel_err": result["sigkill"]["wall_rel_err"],
        "restore_replay_s": result["sigkill"]["restore_replay_s"],
        "rollback_discarded_s": result["nan"]["rollback_discarded_s"],
    }
    return result


def _in_order(kinds, first, second) -> bool:
    try:
        return kinds.index(first) < kinds.index(second)
    except ValueError:
        return False


def check(result: dict) -> list:
    """-> list of violated invariants (empty = pass)."""
    bad = []
    tol = result["tolerance"]
    c = result["clean"]
    if c["rc"] != 0:
        bad.append(f"clean run failed rc={c['rc']}")
    if not c["sum"]["exact"]:
        bad.append(f"clean account does not sum to wall: "
                   f"{c['sum']}")
    if c["wall_rel_err"] is None or c["wall_rel_err"] > tol:
        bad.append(f"clean ledger wall {c['ledger_wall_s']}s vs "
                   f"parent-measured {c['parent_wall_s']}s: relative "
                   f"error {c['wall_rel_err']} > {tol}")
    if c["alerts_fired"] != 0:
        bad.append(f"clean run fired {c['alerts_fired']} alert(s); "
                   f"a healthy run must fire zero")
    if not c["journal_events"]:
        bad.append("clean run journaled zero events (the session "
                   "close event alone should appear)")
    s = result["sigkill"]
    if s["crash_rc"] != -signal.SIGKILL:
        bad.append(f"sigkill child exited {s['crash_rc']}, not "
                   f"-SIGKILL — the crash never happened")
    if s["resume_rc"] != 0:
        bad.append(f"sigkill resume failed rc={s['resume_rc']}")
    if s["attempts"] != 2:
        bad.append(f"resumed ledger reports attempts="
                   f"{s['attempts']}, expected 2 — the account did "
                   f"not persist through the checkpoint manifest")
    if not s["sum"]["exact"]:
        bad.append(f"resumed account does not sum to wall: "
                   f"{s['sum']}")
    if not s["restore_replay_s"] or s["restore_replay_s"] <= 0:
        bad.append(f"restore_replay badput is "
                   f"{s['restore_replay_s']!r}; the restore-verify "
                   f"wall must be attributed")
    if not s["eviction_downtime_s"] or s["eviction_downtime_s"] <= 0:
        bad.append(f"eviction_downtime badput is "
                   f"{s['eviction_downtime_s']!r}; the save->respawn "
                   f"gap must be attributed")
    if s["wall_rel_err"] is None or s["wall_rel_err"] > tol:
        bad.append(f"cross-attempt ledger wall {s['ledger_wall_s']}s "
                   f"vs parent-measured {s['parent_wall_s']}s: "
                   f"relative error {s['wall_rel_err']} > {tol}")
    n = result["nan"]
    if n["rc"] != 0:
        bad.append(f"NaN-rollback run failed rc={n['rc']}")
    if not n["rollback_discarded_s"] or n["rollback_discarded_s"] <= 0:
        bad.append(f"rollback_discarded badput is "
                   f"{n['rollback_discarded_s']!r}; discarded step "
                   f"time must land in its own class")
    if ("recovery", "nonfinite_rollback") not in n["journal_kinds"]:
        bad.append(f"journal carries no recovery/nonfinite_rollback "
                   f"event (got {n['journal_kinds']})")
    if ("ops", "rollback_discarded") not in n["journal_kinds"]:
        bad.append(f"journal carries no ops/rollback_discarded event "
                   f"(got {n['journal_kinds']})")
    if not n["rollback_before_discard"]:
        bad.append("journal order broken: the rollback event must "
                   "precede its discard accounting")
    return bad


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--out", default="")
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--flight-dir", default="")
    ap.add_argument("--journal", default="")
    ap.add_argument("--steps", type=int, default=STEPS)
    ap.add_argument("--crash-at", type=int, default=-1)
    ap.add_argument("--nan-at", default="")
    ap.add_argument("--recovery", action="store_true")
    args = ap.parse_args(argv)
    if args.child:
        return child_main(args)
    result = measure(steps=args.steps)
    violations = check(result)
    result["violations"] = violations
    result["ok"] = not violations
    print(json.dumps(result, indent=2, default=str))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
