"""Prefix-reuse guard: warm TTFT beats cold, tokens exactly equal,
zero recompiles, zero leaks, zero cross-tenant visibility.

ISSUE 15 acceptance, enforced in tier-1
(tests/test_prefix_cache.py::test_prefix_reuse_guard) and runnable
directly::

    JAX_PLATFORMS=cpu python tools/check_prefix_reuse.py

Four contracts over a tiny-NMT continuous-decode rig at >= 50%
shared-prefix load (tools/loadgen.py ``shared_prefix_feed`` — the
shared/unique split is a pure function of the request index, so every
phase replays the EXACT same request stream):

* **exact reuse** — every token stream served through the prefix cache
  (cold round, warm round, extended-cap round) is BIT-identical to the
  sharing-disabled session fed the same requests: reuse is a latency
  optimization, never a result change.
* **warm TTFT** — the same request stream re-submitted against the
  populated cache has a p50 TTFT measurably below the sharing-disabled
  A/B on the same rig (full hits complete with zero device dispatches;
  the guard requires warm <= 0.8x cold, the measured gap is far
  larger).
* **zero serve-time compiles / zero leaked pages** — the prefix paths
  (replay activation, COW page copy, eviction) stay inside the closed
  AOT signature set (``jax.monitoring`` backend-compile witness at 0)
  and after close every pool page is back (the cache's held pages are
  released at drain; ref-count accounting means a page leak cannot
  hide behind sharing).
* **tenant isolation under churn** — tenant B submitting tenant A's
  EXACT sources gets zero prefix hits (the per-tenant radix roots make
  cross-tenant mapping structurally impossible; the hit counter is the
  witness that no foreign page was ever mapped) while its OUTPUTS
  still equal A's (greedy determinism — proving the isolation is not
  hiding a result difference), and an eviction + COW churn phase on a
  starved pool (evictions > 0, COW copies > 0, deferrals allowed)
  keeps every invariant above.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_compile_events = {"n": 0, "active": False}


def _install_listener():
    import jax

    def _listen(event, duration, **kw):
        if _compile_events["active"] and "backend_compile" in event:
            _compile_events["n"] += 1

    jax.monitoring.register_event_duration_secs_listener(_listen)


def _pct(vals, q):
    from parallax_tpu.obs.metrics import nearest_rank
    v = nearest_rank(sorted(vals), q)
    return round(v, 3) if v is not None else None


def _serve_round(sess, feeds, caps, submit_kw=None,
                 timeout_s: float = 300.0):
    """Submit every (feed, cap) and gather ``(tokens, ttft_ms)`` in
    order — outputs kept so rounds can be diffed token-for-token."""
    reqs = [sess.submit(f, max_new_tokens=c, **(submit_kw or {}))
            for f, c in zip(feeds, caps)]
    outs, ttfts = [], []
    for r in reqs:
        outs.append([int(t) for t in r.result(timeout=timeout_s)])
        t_first = r.t_first_token if r.t_first_token is not None \
            else r.t_done
        ttfts.append((t_first - r.t_enqueue) * 1e3)
    return outs, ttfts


def _decode_rig(prefix_cache: bool, slots: int = 8,
                pool_pages: int = 72, **kw):
    # pool = 3x the slots' max working set (8 slots x 3 pages): the
    # cache needs headroom BEYOND in-flight pages to hold prefixes
    # between requests — a pool sized exactly to the working set
    # degenerates into evict-on-every-retire
    from tools import loadgen
    return loadgen.demo_decode_session(
        slots=slots, T=12, Ts=8, page_size=4, pool_pages=pool_pages,
        model_dim=32, num_layers=2, vocab=64,
        prefill_chunk_layers=None, spec_tokens=0, speculative=False,
        prefix_cache=prefix_cache, **kw)


def measure(n_requests: int = 36, prefix_share: float = 0.6) -> dict:
    import numpy as np  # noqa: F401  (loadgen feeds are numpy)

    from tools import loadgen

    _install_listener()
    make_feed = loadgen.shared_prefix_feed(
        Ts=8, vocab=64, prefix_share=prefix_share, pool_size=3)
    feeds = [make_feed(i) for i in range(n_requests)]
    # mixed caps: odd requests stop mid-page so the warm round's
    # longer caps exercise the COW boundary, not just full replays
    caps = [7 if i % 2 else 12 for i in range(n_requests)]

    # -- baseline: sharing DISABLED, same stream -----------------------
    base_sess, _ = _decode_rig(prefix_cache=False)
    try:
        _compile_events["n"] = 0
        _compile_events["active"] = True
        t0 = time.perf_counter()
        base_outs, base_ttfts = _serve_round(base_sess, feeds, caps)
        base_wall = time.perf_counter() - t0
        _compile_events["active"] = False
        base_stats = base_sess.stats()
        base_alloc = base_sess._scheduler._alloc
    finally:
        base_sess.close()

    # -- prefix cache ON: cold round, warm round, extended caps --------
    sess, _ = _decode_rig(prefix_cache=True)
    try:
        _compile_events["active"] = True
        t0 = time.perf_counter()
        cold_outs, cold_ttfts = _serve_round(sess, feeds, caps)
        cold_wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        warm_outs, warm_ttfts = _serve_round(sess, feeds, caps)
        warm_wall = time.perf_counter() - t0
        # extended caps: every capped-at-7 request re-runs at 12 — a
        # partial hit that must COW the boundary page and CONTINUE
        ext_caps = [12] * n_requests
        ext_outs, _ = _serve_round(sess, feeds, ext_caps)
        _compile_events["active"] = False
        stats = sess.stats()
        pstats = sess.prefix_stats()
        alloc = sess._scheduler._alloc
    finally:
        sess.close()
    # baseline for the extended round, from the sharing-off rig
    base2_sess, _ = _decode_rig(prefix_cache=False)
    try:
        ext_base_outs, _ = _serve_round(base2_sess, feeds, ext_caps)
    finally:
        base2_sess.close()

    tok_mismatches = sum(
        1 for a, b in zip(base_outs, cold_outs) if a != b) + sum(
        1 for a, b in zip(base_outs, warm_outs) if a != b) + sum(
        1 for a, b in zip(ext_base_outs, ext_outs) if a != b)

    # -- tenant isolation + eviction/COW churn on a starved pool -------
    tsess, _ = _decode_rig(prefix_cache=True, slots=4, pool_pages=9)
    iso = {}
    try:
        _compile_events["active"] = True
        pool_feeds = [make_feed(i) for i in (1, 3, 5)]  # shared pool
        a_caps = [7, 12, 7]
        a_outs, _ = _serve_round(tsess, pool_feeds, a_caps,
                                 submit_kw={"tenant": "tenant-a"})
        hits_after_a = tsess.stats()["serve.prefix.hits"]
        b_outs, _ = _serve_round(tsess, pool_feeds, a_caps,
                                 submit_kw={"tenant": "tenant-b"})
        st = tsess.stats()
        hits_after_b = st["serve.prefix.hits"]
        # churn: 8 distinct max-cap sequences through a 9-page pool —
        # cache pressure MUST evict (LRU, unpinned only) and the
        # re-submitted pool sources exercise COW on partial replays
        churn_feeds = [make_feed(100 + i) for i in range(8)] \
            + pool_feeds
        churn_caps = [12] * 8 + [12, 12, 12]
        c_outs, _ = _serve_round(
            tsess, churn_feeds, churn_caps,
            submit_kw={"tenant": "tenant-a"})
        a2_outs, _ = _serve_round(tsess, pool_feeds, a_caps,
                                  submit_kw={"tenant": "tenant-a"})
        _compile_events["active"] = False
        tstats = tsess.stats()
        tp = tsess.prefix_stats()
        talloc = tsess._scheduler._alloc
        iso = {
            "a_hits": hits_after_a,
            "b_hits_delta": hits_after_b - hits_after_a,
            "b_outputs_equal_a": [list(x) for x in b_outs]
            == [list(x) for x in a_outs],
            "a_replay_outputs_equal": a2_outs == a_outs,
            "evictions": tstats.get("serve.prefix.evictions"),
            "cow_copies": tstats.get("serve.prefix.cow_copies"),
            "deferred": tstats.get("serve.kv_refill_deferred", 0),
            "cache": tp,
        }
    finally:
        tsess.close()

    return {
        "requests_per_round": n_requests,
        "prefix_share": prefix_share,
        "ttft_ms_p50_cold_nosharing": _pct(base_ttfts, 0.5),
        "ttft_ms_p50_cold": _pct(cold_ttfts, 0.5),
        "ttft_ms_p50_warm": _pct(warm_ttfts, 0.5),
        "ttft_ms_p95_warm": _pct(warm_ttfts, 0.95),
        "wall_s": {"nosharing": round(base_wall, 3),
                   "cold": round(cold_wall, 3),
                   "warm": round(warm_wall, 3)},
        "tokens_per_sec_warm": round(
            sum(len(o) for o in warm_outs) / warm_wall, 2)
        if warm_wall > 0 else None,
        "tokens_per_sec_nosharing": round(
            sum(len(o) for o in base_outs) / base_wall, 2)
        if base_wall > 0 else None,
        "token_mismatches": tok_mismatches,
        "hit_rate": stats.get("serve.prefix.hit_rate"),
        "hits": stats.get("serve.prefix.hits"),
        "misses": stats.get("serve.prefix.misses"),
        "full_hits": stats.get("serve.prefix.full_hits"),
        "cow_copies": stats.get("serve.prefix.cow_copies"),
        "replayed_tokens": stats.get("serve.prefix.replayed_tokens"),
        "prefill_tokens_skipped": stats.get(
            "serve.prefix.prefill_tokens_skipped"),
        "evictions": stats.get("serve.prefix.evictions"),
        "kv_sharing_ratio_seen": stats.get("serve.kv_sharing_ratio"),
        "prefix_cache": pstats,
        "recompiles": (stats.get("serve.recompiles", 0)
                       + base_stats.get("serve.recompiles", 0)),
        "serve_time_xla_compiles": _compile_events["n"],
        # post-close page accounting: the allocator itself, AFTER the
        # drain released the cache — a leak cannot hide behind sharing
        # because in_use counts physical pages once
        "pages_in_use_after_close": {
            "nosharing": base_alloc.in_use,
            "prefix": alloc.in_use,
            "tenant_rig": talloc.in_use,
        },
        "tenant_isolation": iso,
    }


def check(result: dict) -> list:
    """-> list of violated invariants (empty = pass)."""
    bad = []
    if result["token_mismatches"] != 0:
        bad.append(f"{result['token_mismatches']} request(s) decoded "
                   f"DIFFERENT tokens with the prefix cache on — "
                   f"exact-reuse broken")
    cold = result["ttft_ms_p50_cold_nosharing"]
    warm = result["ttft_ms_p50_warm"]
    if cold is None or warm is None:
        bad.append("missing TTFT percentiles")
    elif warm > 0.8 * cold:
        bad.append(f"warm TTFT p50 {warm}ms not measurably below the "
                   f"no-sharing cold p50 {cold}ms (need <= 0.8x)")
    if result["serve_time_xla_compiles"] != 0:
        bad.append(f"{result['serve_time_xla_compiles']} XLA "
                   f"compile(s) fired during prefix-cached serving — "
                   f"the replay/COW/eviction paths leaked a signature")
    if result["recompiles"] != 0:
        bad.append(f"serve.recompiles = {result['recompiles']}")
    if (result["hit_rate"] or 0) < 0.4:
        bad.append(f"prefix hit rate {result['hit_rate']} < 0.4 at "
                   f"{result['prefix_share']} shared-prefix load")
    if not result["full_hits"]:
        bad.append("no full hit — the warm round never replayed a "
                   "complete cached sequence")
    if not result["cow_copies"]:
        bad.append("no COW copy — the extended-cap round never hit "
                   "the divergence boundary")
    for name, n in result["pages_in_use_after_close"].items():
        if n != 0:
            bad.append(f"{n} page(s) leaked after close ({name} rig)")
    iso = result["tenant_isolation"]
    if iso.get("b_hits_delta", 1) != 0:
        bad.append(f"tenant B saw {iso.get('b_hits_delta')} prefix "
                   f"hit(s) on tenant A's sources — cross-tenant "
                   f"visibility")
    if not iso.get("b_outputs_equal_a"):
        bad.append("tenant B's outputs differ from tenant A's for "
                   "identical requests (isolation is masking a "
                   "result bug)")
    if not iso.get("a_replay_outputs_equal"):
        bad.append("tenant A's post-churn replay changed its tokens")
    if not iso.get("evictions"):
        bad.append("the starved-pool churn phase evicted nothing — "
                   "the rig no longer exercises LRU eviction")
    return bad


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=36)
    ap.add_argument("--prefix-share", type=float, default=0.6)
    args = ap.parse_args(argv)
    result = measure(n_requests=args.requests,
                     prefix_share=args.prefix_share)
    violations = check(result)
    result["violations"] = violations
    result["ok"] = not violations
    print(json.dumps(result, indent=2, default=str))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
