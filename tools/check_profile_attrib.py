"""Guard: measured per-op attribution must explain the device step wall.

ISSUE 13 acceptance, the ``check_serve_slo``/``check_train_faults``
pattern: drive a real profiled window end to end on the tier-1 CPU
backend and assert the plan observatory's core contracts —

  1. the per-op attribution accounts for >= 90% of the measured
     device step wall, with the residual reported explicitly (never
     hidden inside a category);
  2. the taxonomy is live: collectives are seen on the multi-device
     mesh, category shares sum to ~1, and the dense-vs-sparse split
     attributes real self-time to the sparse (row-sharded table)
     path on an embedding-bearing model;
  3. the calibration loop closes: per-term predicted/measured ratios
     derive from the same window, round-trip through the persisted
     calibration file (tune/calibrate.py), and survive reload;
  4. memwatch's compiled-memory account resolves off the warmed
     executables.

Run directly::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/check_profile_attrib.py

or via tier-1 (tests/test_profile.py subprocess guard). bench.py runs
it as the ``profile`` block's child; the JSON it prints is the block.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_"
                                 "count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

V, D, BATCH = 8192, 32, 256


def _model():
    import jax
    import jax.numpy as jnp
    import optax

    import parallax_tpu as parallax
    from parallax_tpu.ops import embedding as emb_ops

    def init_fn(rng):
        return {"emb": jax.random.normal(rng, (V, D)) * 0.1,
                "w": jnp.eye(D) * 0.1}

    def loss_fn(params, batch):
        rows = emb_ops.embedding_lookup(params["emb"], batch["ids"])
        return jnp.mean((rows @ params["w"]) ** 2)

    return parallax.Model(init_fn, loss_fn,
                          optimizer=optax.sgd(0.1))


def measure(steps: int = 6, warm: int = 4) -> dict:
    """One profiled window end to end; returns the JSON-ready report
    (the bench ``profile`` block)."""
    import jax
    import numpy as np

    import parallax_tpu as parallax
    from parallax_tpu.obs import memwatch
    from parallax_tpu.tune import calibrate, costmodel

    sess, *_ = parallax.parallel_run(
        _model(),
        parallax_config=parallax.Config(
            run_option="HYBRID", search_partitions=False,
            eager_fetch=True))
    try:
        rng = np.random.default_rng(0)
        feed = {"ids": rng.integers(0, V, (BATCH,)).astype(np.int32)}
        sess.prepare(feed)
        # warmup BEFORE profiling: the AOT executable is what the
        # window's steps dispatch, so the HLO index used for
        # layer/sparse mapping is the exact executed module
        sess.warmup(batch_sizes=[BATCH])
        for _ in range(warm):
            float(sess.run("loss", feed_dict=feed))
        outdir = sess.profile_steps(steps)
        for _ in range(steps):
            float(sess.run("loss", feed_dict=feed))
        attrib = sess.profile_summary()
        if not attrib or attrib.get("error"):
            raise RuntimeError(f"attribution failed: {attrib}")

        shares = {cat: row["share"]
                  for cat, row in attrib["by_category"].items()}

        # calibration off the same window: the cost model's per-term
        # prediction for the live plan vs the measured aggregates
        inputs = costmodel.inputs_from_engine(sess.engine)
        pc = costmodel.predict(sess.plan, inputs)
        predicted = calibrate.predicted_terms_from_cost(pc.terms)
        measured = calibrate.measured_terms_from_attribution(
            attrib, jax.device_count())
        record = calibrate.build_record(
            predicted, measured, basis="cpu-nominal",
            meta={"tool": "check_profile_attrib",
                  "plan": sess.plan.describe()})
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "calibration.json")
            calibrate.save(path, record)
            reloaded = calibrate.load(path)
            roundtrip_ok = (reloaded is not None
                            and calibrate.ratios(reloaded)
                            == calibrate.ratios(record))

        compiled = memwatch.compiled_step_memory(sess.engine)
        ratios = calibrate.ratios(record) or {}
        return {
            "attribution_coverage": attrib["coverage"],
            "residual_ms": attrib["residual_ms"],
            "attributed_ms": attrib["attributed_ms"],
            "wall_ms": attrib["wall_ms"],
            "window_span_ms": attrib["window_span_ms"],
            "inter_step_ms": attrib["inter_step_ms"],
            "step_wall_ms": attrib["step_wall_ms"],
            "steps": attrib["steps"],
            "events": attrib["events"],
            "track_basis": attrib["track_basis"],
            "shares": shares,
            "collectives": attrib["collectives"],
            "top_ops": attrib["top_ops"][:5],
            "dense_sparse": attrib["dense_sparse"],
            "calibration": {
                "on_chip_predicted_over_measured":
                    ratios.get("on_chip"),
                "wire_predicted_over_measured": ratios.get("wire"),
                "terms": record["terms"],
            },
            "calibration_roundtrip_ok": roundtrip_ok,
            "memwatch": {
                "compiled_peak_bytes": (compiled or {}).get(
                    "peak_bytes"),
                "compiled_basis": (compiled or {}).get("basis"),
            },
            "capture_dir": outdir,
        }
    finally:
        sess.close()


def check(res: dict, min_coverage: float = 0.90) -> list:
    """Violation list (empty = pass) over one measure() report."""
    v = []
    cov = res.get("attribution_coverage")
    if not isinstance(cov, (int, float)) or cov < min_coverage:
        v.append(f"attribution coverage {cov!r} < {min_coverage} of "
                 f"the measured device step wall")
    if "residual_ms" not in res \
            or not isinstance(res["residual_ms"], (int, float)) \
            or res["residual_ms"] < 0:
        v.append("residual_ms missing/negative — the unattributed "
                 "share must be reported explicitly")
    shares = res.get("shares") or {}
    total = sum(shares.values())
    if abs(total - 1.0) > 0.02:
        v.append(f"category shares sum to {total:.4f}, not ~1")
    if shares.get("collective", 0) <= 0:
        v.append("no collective self-time attributed on a "
                 "multi-device mesh")
    ds = res.get("dense_sparse") or {}
    if ds.get("sparse_self_ms", 0) <= 0:
        v.append("dense/sparse split attributed no time to the "
                 "sparse table path on an embedding model")
    cal = res.get("calibration") or {}
    for term in ("on_chip_predicted_over_measured",
                 "wire_predicted_over_measured"):
        r = cal.get(term)
        if not isinstance(r, (int, float)) or r <= 0:
            v.append(f"calibration {term} is {r!r}, expected > 0")
    if not res.get("calibration_roundtrip_ok"):
        v.append("calibration file round-trip failed")
    if not res.get("memwatch", {}).get("compiled_peak_bytes"):
        v.append("memwatch compiled-memory account did not resolve "
                 "off the warmed executables")
    return v


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--min-coverage", type=float, default=0.90)
    args = ap.parse_args(argv)
    res = measure(steps=args.steps)
    violations = check(res, args.min_coverage)
    res["ok"] = not violations
    res["violations"] = violations or None
    print(json.dumps(res))
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
