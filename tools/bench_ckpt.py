"""Checkpoint cost block: save/restore latency, bytes, step overhead.

Prices the ckpt/ subsystem for bench.py's ``ckpt`` block (ISSUE 9):

* ``save_ms`` / ``restore_ms`` — full synchronous save and verified
  restore of a realistically-sized train state (embedding table +
  adam moments), through the atomic store.
* ``ckpt_bytes`` — one committed checkpoint's on-disk size.
* ``async_dispatch_ms`` vs ``save_ms`` — the async path's
  critical-path cost is ONLY the host snapshot + writer handoff
  (serialization/fsync happen off-thread); the synchronous path pays
  the whole write on the dispatch thread. That pair is the A/B the
  acceptance criterion names.
* ``async_step_overhead_pct`` — the async dispatch cost amortized
  over the save cadence as a percentage of measured step time
  (the decomposed methodology of tools/check_obs_overhead.py: wall
  A/B across whole training runs drowns a sub-millisecond cost in
  host noise; the decomposition prices exactly the critical-path
  work). Budget: <= 2% (tier-1-enforced in tests/test_ckpt.py).

Runnable directly::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/bench_ckpt.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

OVERHEAD_BUDGET_PCT = 2.0


def _build_model(V: int = 2048, D: int = 128):
    import jax
    import jax.numpy as jnp
    import optax

    import parallax_tpu as parallax
    from parallax_tpu.ops import embedding as emb_ops

    def init_fn(rng):
        k1, k2 = jax.random.split(rng)
        return {"emb": jax.random.normal(k1, (V, D)) * 0.1,
                "w": jax.random.normal(k2, (D,)) * 0.1}

    def loss_fn(params, batch):
        rows = emb_ops.embedding_lookup(params["emb"], batch["ids"])
        pred = rows @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    return parallax.Model(init_fn, loss_fn,
                          optimizer=optax.adam(0.01)), V


def _batch(rng, n, V):
    import numpy as np
    return {"ids": rng.integers(0, V, (n,)).astype(np.int32),
            "y": rng.standard_normal(n).astype(np.float32)}


def measure(steps: int = 30, save_every: int = 25, reps: int = 3,
            batch: int = 256) -> dict:
    import numpy as np

    import parallax_tpu as parallax
    from parallax_tpu.ckpt.hook import CheckpointHook
    from parallax_tpu.ckpt.store import CheckpointStore, _dir_bytes
    from parallax_tpu.ckpt import snapshot as snap_lib

    model, V = _build_model()
    sess, *_ = parallax.parallel_run(
        model, parallax_config=parallax.Config(
            run_option="HYBRID", search_partitions=False))
    rng = np.random.default_rng(0)
    try:
        # warmup + steady-state step time (no checkpointing at all)
        for _ in range(5):
            sess.run("loss", feed_dict=_batch(rng, batch, V))
        times = []
        for _ in range(steps):
            t0 = time.perf_counter()
            float(sess.run("loss", feed_dict=_batch(rng, batch, V)))
            times.append(time.perf_counter() - t0)
        step_ms = float(np.median(times)) * 1e3
        state = sess.state

        work = tempfile.mkdtemp(prefix="bench_ckpt_")

        # synchronous save+restore latency through the atomic store
        save_s, restore_s = [], []
        store = CheckpointStore(os.path.join(work, "sync"),
                                max_to_keep=None)
        for i in range(reps):
            t0 = time.perf_counter()
            store.save(i + 1, state)
            save_s.append(time.perf_counter() - t0)
        ckpt_bytes = _dir_bytes(os.path.join(work, "sync", str(reps)))
        for _ in range(reps):
            t0 = time.perf_counter()
            out = store.restore_latest(state)
            assert out is not None
            restore_s.append(time.perf_counter() - t0)

        # async dispatch cost: the ONLY critical-path work is the host
        # snapshot + thread handoff (what CheckpointHook._save pays on
        # the dispatch thread before returning)
        import threading
        async_s = []
        for _ in range(reps):
            t0 = time.perf_counter()
            snap = snap_lib.host_snapshot(state, step=0)
            t = threading.Thread(target=lambda: None, daemon=True)
            t.start()
            async_s.append(time.perf_counter() - t0)
            t.join()
            del snap
        async_ms = float(np.median(async_s)) * 1e3
        save_ms = float(np.median(save_s)) * 1e3
        restore_ms = float(np.median(restore_s)) * 1e3

        # end-to-end witness: a session configured async really does
        # commit (the A/B partner for the decomposed number)
        hook = CheckpointHook(
            parallax.CheckPointConfig(
                ckpt_dir=os.path.join(work, "async"),
                save_ckpt_steps=1, async_save=True),
            worker_id=0)
        t0 = time.perf_counter()
        hook.maybe_save(1, state)
        async_dispatch_measured = (time.perf_counter() - t0) * 1e3
        hook.close()
        committed = CheckpointStore(
            os.path.join(work, "async")).complete_steps()

        async_pct = 100.0 * async_ms / (save_every * step_ms)
        sync_pct = 100.0 * save_ms / (save_every * step_ms)
        return {
            "step_ms": round(step_ms, 3),
            "save_every": save_every,
            "save_ms": round(save_ms, 3),
            "restore_ms": round(restore_ms, 3),
            "ckpt_bytes": ckpt_bytes,
            "async_dispatch_ms": round(async_ms, 3),
            "async_dispatch_ms_via_hook": round(
                async_dispatch_measured, 3),
            "async_commit_witnessed": committed == [1],
            "async_step_overhead_pct": round(async_pct, 3),
            "sync_step_overhead_pct": round(sync_pct, 3),
            "overhead_budget_pct": OVERHEAD_BUDGET_PCT,
            "ok": bool(async_pct <= OVERHEAD_BUDGET_PCT
                       and committed == [1]),
        }
    finally:
        sess.close()


def main() -> int:
    result = measure()
    print(json.dumps(result, indent=2))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
