"""Training chaos guard: SIGKILL, torn saves, NaN bursts — gated.

ISSUE 9 acceptance, enforced in tier-1
(tests/test_ckpt.py::test_train_chaos_guard via the established
subprocess-driver pattern) and runnable directly::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/check_train_faults.py

Four phases, each over the deterministic simple-model training loop
(batch *i* is a pure function of *i*, so any two runs that agree on
state + cursor agree on every loss bit):

* **baseline** — N uninterrupted steps; the per-step losses (recorded
  as ``float.hex()``) are the bit-identity reference.
* **sigkill** — a worker trains with checkpoints every k steps and
  SIGKILLs itself mid-run (no atexit, no flushing — the hardware
  failure model). The relaunched worker restores the last committed
  checkpoint, skips ``data_cursor`` batches of the same stream, and
  finishes. Contract: every post-resume loss is BIT-identical to the
  uninterrupted run, and the resumed worker leaves a ``resume``
  flight artifact.
* **torn** — the worker dies INSIDE a checkpoint save, after the
  shard files are durable but before the manifest commit
  (``PARALLAX_CKPT_FAULT=torn_manifest``). The relaunch must detect
  the torn directory, fall back to the previous complete checkpoint
  with a loud log + ``ckpt_torn`` flight artifact, and still finish
  bit-identical to the uninterrupted run.
* **nan** — a NaN batch is injected with auto-recovery enabled
  (``RecoveryConfig``): the worker must roll back to its in-memory
  last-good snapshot, skip the offending batch, finish ALL remaining
  batches with a finite final loss and no human intervention, and
  leave a ``nonfinite_rollback`` flight artifact. A second injection
  run with every batch poisoned must SURRENDER within the bounded
  retry budget (``recovery_surrender`` artifact, nonzero exit).
* **preemption** — the parent SIGTERMs a mid-training worker; the
  worker's handler leaves a ``preemption`` flight artifact and ONE
  final checkpoint at its current step before dying with the
  standard SIGTERM status.

bench.py stamps the ``bench`` sub-dict as the ``ckpt.faults`` block.
All numbers are CPU-relative until the TPU relay appears.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

STEPS = 12
CKPT_EVERY = 4


# ---------------------------------------------------------------------------
# child: one deterministic training run
# ---------------------------------------------------------------------------

def _batch_for(i: int, nan: bool = False):
    import numpy as np
    from parallax_tpu.models import simple
    b = simple.make_batch(np.random.default_rng(1000 + i), 32)
    if nan:
        b["x"] = b["x"] * np.nan
    return b


def child_main(args) -> int:
    import numpy as np  # noqa: F401

    import parallax_tpu as parallax
    from parallax_tpu.models import simple

    nan_at = {int(s) for s in args.nan_at.split(",") if s}
    cfg = parallax.Config(
        run_option="AR", search_partitions=False,
        flight_dir=args.flight_dir or None,
        ckpt_config=parallax.CheckPointConfig(
            ckpt_dir=args.ckpt_dir or None,
            save_ckpt_steps=CKPT_EVERY if args.ckpt_dir else None),
        recovery_config=parallax.RecoveryConfig(
            enabled=bool(args.recovery), snapshot_every_steps=2,
            max_retries=2),
        # numerics provenance only on the recovery phases: the
        # sigkill/torn phases compare losses bit-exactly against the
        # uninstrumented baseline, so their graphs must stay identical
        numerics_interval=2 if args.recovery else 0)
    sess, *_ = parallax.parallel_run(simple.build_model(0.1),
                                     parallax_config=cfg)
    start = sess.prepare(_batch_for(0))
    cursor = sess.data_cursor
    with open(args.out, "a") as f:
        f.write(f"# start={start} cursor={cursor}\n")
    i = cursor
    while i < args.steps:
        batch = _batch_for(i, nan=i in nan_at)
        loss = sess.run("loss", feed_dict=batch)
        val = float(loss)
        # losses keyed by BATCH index (the cursor), hex-exact: a NaN
        # rollback rewinds the step counter but never the cursor, so
        # the cursor is the only stable join key across runs
        with open(args.out, "a") as f:
            f.write(f"{i} {val.hex()}\n")
            f.flush()
        if args.crash_at >= 0 and i + 1 >= args.crash_at:
            os.kill(os.getpid(), signal.SIGKILL)  # no cleanup, ever
        if args.hang_after >= 0 and i + 1 >= args.hang_after:
            # park for the parent's SIGTERM (preemption phase)
            while True:
                time.sleep(0.1)
        i += 1
    with open(args.out, "a") as f:
        f.write(f"# done step={sess._host_step} "
                f"cursor={sess.data_cursor} "
                f"rollbacks={sess._recovery.total_rollbacks if sess._recovery else 0}\n")
    sess.close()
    return 0


# ---------------------------------------------------------------------------
# parent: orchestrate the phases
# ---------------------------------------------------------------------------

def _run_child(out, ckpt_dir="", flight_dir="", crash_at=-1,
               nan_at="", recovery=False, hang_after=-1, env=None,
               timeout=300.0, steps=STEPS):
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--out", out, "--ckpt-dir", ckpt_dir,
           "--flight-dir", flight_dir, "--steps", str(steps),
           "--crash-at", str(crash_at), "--nan-at", nan_at,
           "--hang-after", str(hang_after)]
    if recovery:
        cmd.append("--recovery")
    full_env = dict(os.environ, JAX_PLATFORMS="cpu")
    full_env.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    full_env.update(env or {})
    return subprocess.run(cmd, env=full_env, timeout=timeout,
                          capture_output=True, text=True)


def _read_losses(path) -> dict:
    """{batch index: loss hex} plus the '#' metadata lines."""
    out, meta = {}, []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                if line.startswith("#"):
                    meta.append(line)
                    continue
                i, hx = line.split()
                out[int(i)] = hx
    except OSError:
        pass
    return {"losses": out, "meta": meta}


def _flight_classes(flight_dir) -> list:
    try:
        return sorted({os.path.basename(p).split("_", 1)[1]
                       .rsplit("_", 2)[0]
                       for p in os.listdir(flight_dir)})
    except OSError:
        return []


def measure(steps: int = STEPS) -> dict:
    result: dict = {"steps": steps, "ckpt_every": CKPT_EVERY}
    work = tempfile.mkdtemp(prefix="train_faults_")

    # -- baseline: uninterrupted ---------------------------------------
    base_out = os.path.join(work, "baseline.losses")
    t0 = time.perf_counter()
    p = _run_child(base_out, steps=steps)
    result["baseline"] = {
        "rc": p.returncode,
        "seconds": round(time.perf_counter() - t0, 3),
    }
    baseline = _read_losses(base_out)["losses"]
    result["baseline"]["recorded"] = len(baseline)

    # -- phase 1: SIGKILL mid-run, exact resume ------------------------
    ck1 = os.path.join(work, "ck_sigkill")
    fl1 = os.path.join(work, "fl_sigkill")
    out1 = os.path.join(work, "sigkill.losses")
    crash_at = CKPT_EVERY * 2 + 1  # past the 2nd checkpoint commit
    p1 = _run_child(out1, ckpt_dir=ck1, flight_dir=fl1,
                    crash_at=crash_at, steps=steps)
    t0 = time.perf_counter()
    p1b = _run_child(out1, ckpt_dir=ck1, flight_dir=fl1, steps=steps)
    r1 = _read_losses(out1)
    resumed_from = None
    for m in r1["meta"]:
        if "start=" in m and "start=0" not in m:
            resumed_from = int(m.split("start=")[1].split()[0])
    mism1 = [i for i, hx in r1["losses"].items()
             if baseline.get(i) != hx]
    result["sigkill"] = {
        "crash_rc": p1.returncode,
        "resume_rc": p1b.returncode,
        "resume_seconds": round(time.perf_counter() - t0, 3),
        "crash_at_batch": crash_at,
        "resumed_from_step": resumed_from,
        "recorded": len(r1["losses"]),
        "loss_mismatches": mism1,
        "flight_classes": _flight_classes(fl1),
    }

    # -- phase 2: crash mid-checkpoint-write (torn manifest) -----------
    ck2 = os.path.join(work, "ck_torn")
    fl2 = os.path.join(work, "fl_torn")
    out2 = os.path.join(work, "torn.losses")
    # the injected fault kills the SECOND save (step 8) mid-commit:
    # the env knob arms every save, so let the first one through by
    # arming only the child that will reach step 8 — simplest is to
    # arm from the start and crash on the FIRST save, leaving zero
    # complete checkpoints... instead we want a fallback target, so:
    # run once cleanly to step 5 (commit at 4), then run armed (the
    # step-8 save dies mid-commit), then resume.
    p2a = _run_child(out2, ckpt_dir=ck2, flight_dir=fl2,
                     crash_at=CKPT_EVERY + 1, steps=steps)
    p2b = _run_child(out2, ckpt_dir=ck2, flight_dir=fl2, steps=steps,
                     env={"PARALLAX_CKPT_FAULT": "torn_manifest"})
    torn_dirs = sorted(
        d for d in os.listdir(ck2)
        if d.isdigit() and not os.path.exists(
            os.path.join(ck2, d, "manifest.json")))
    t0 = time.perf_counter()
    p2c = _run_child(out2, ckpt_dir=ck2, flight_dir=fl2, steps=steps)
    r2 = _read_losses(out2)
    resumed2 = [int(m.split("start=")[1].split()[0])
                for m in r2["meta"] if "start=" in m]
    mism2 = [i for i, hx in r2["losses"].items()
             if baseline.get(i) != hx]
    result["torn"] = {
        "first_rc": p2a.returncode,
        "torn_rc": p2b.returncode,
        "resume_rc": p2c.returncode,
        "resume_seconds": round(time.perf_counter() - t0, 3),
        "torn_dirs_observed": torn_dirs,
        "starts": resumed2,
        "loss_mismatches": mism2,
        "fallback_logged": "FELL BACK" in (p2c.stderr or "")
                           or "TORN" in (p2c.stderr or ""),
        "flight_classes": _flight_classes(fl2),
    }

    # -- phase 3: injected NaN burst, auto-recovery --------------------
    fl3 = os.path.join(work, "fl_nan")
    out3 = os.path.join(work, "nan.losses")
    t0 = time.perf_counter()
    p3 = _run_child(out3, flight_dir=fl3, nan_at="6", recovery=True,
                    steps=steps)
    r3 = _read_losses(out3)
    rollbacks = 0
    completed = False
    for m in r3["meta"]:
        if "done" in m:
            completed = True
            rollbacks = int(m.split("rollbacks=")[1])
    finite_final = False
    if r3["losses"]:
        last = float.fromhex(r3["losses"][max(r3["losses"])])
        finite_final = last == last and abs(last) != float("inf")
    # NaN provenance: the rollback artifact must NAME the poisoned
    # stage (feed/x — the injected batch), not just record the trip
    provenance = {"culprit": None, "trail_len": 0, "blast_radius": None}
    try:
        arts = sorted(p for p in os.listdir(fl3)
                      if p.startswith("flight_nonfinite_rollback_"))
        if arts:
            with open(os.path.join(fl3, arts[0])) as f:
                doc = json.load(f)
            det = ((doc.get("trigger") or {}).get("detail")
                   or doc.get("detail") or {})
            prov = det.get("provenance") or {}
            provenance = {
                "culprit": prov.get("culprit"),
                "blast_radius": prov.get("blast_radius"),
                "trail_len": len(det.get("stats_trail") or ()),
            }
    except (OSError, ValueError):
        pass
    result["nan"] = {
        "rc": p3.returncode,
        "seconds": round(time.perf_counter() - t0, 3),
        "completed": completed,
        "rollbacks": rollbacks,
        "recorded": len(r3["losses"]),
        "final_loss_finite": finite_final,
        "flight_classes": _flight_classes(fl3),
        "provenance": provenance,
    }
    # poisoned run: every batch NaN -> bounded surrender, nonzero rc
    fl3b = os.path.join(work, "fl_nan_all")
    out3b = os.path.join(work, "nan_all.losses")
    p3b = _run_child(out3b, flight_dir=fl3b,
                     nan_at=",".join(str(i) for i in range(steps)),
                     recovery=True, steps=steps)
    result["nan"]["surrender_rc"] = p3b.returncode
    result["nan"]["surrender_flight"] = _flight_classes(fl3b)
    result["nan"]["surrendered"] = (
        p3b.returncode != 0
        and "RecoverySurrender" in (p3b.stderr or ""))

    # -- phase 4: SIGTERM preemption notice ----------------------------
    ck4 = os.path.join(work, "ck_preempt")
    fl4 = os.path.join(work, "fl_preempt")
    out4 = os.path.join(work, "preempt.losses")
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--out", out4, "--ckpt-dir", ck4, "--flight-dir", fl4,
           "--steps", str(steps), "--crash-at", "-1",
           "--nan-at", "", "--hang-after", str(CKPT_EVERY + 2)]
    env4 = dict(os.environ, JAX_PLATFORMS="cpu")
    env4.setdefault("XLA_FLAGS",
                    "--xla_force_host_platform_device_count=8")
    proc = subprocess.Popen(cmd, env=env4, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    deadline = time.time() + 240
    # wait until it is parked mid-training (past the hang step)
    while time.time() < deadline:
        if len(_read_losses(out4)["losses"]) >= CKPT_EVERY + 2:
            break
        if proc.poll() is not None:
            break
        time.sleep(0.1)
    time.sleep(0.3)
    t0 = time.perf_counter()
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=120)
    except subprocess.TimeoutExpired:
        proc.kill()
    from parallax_tpu.ckpt.store import CheckpointStore
    final_steps = CheckpointStore(ck4).complete_steps()
    result["preemption"] = {
        "rc": proc.returncode,
        "react_seconds": round(time.perf_counter() - t0, 3),
        "batches_before_sigterm": len(_read_losses(out4)["losses"]),
        "final_checkpoint_steps": final_steps,
        "flight_classes": _flight_classes(fl4),
    }

    c = result
    result["bench"] = {
        "steps": steps,
        "sigkill_resume_seconds": c["sigkill"]["resume_seconds"],
        "torn_fallback_resume_seconds": c["torn"]["resume_seconds"],
        "nan_recovery_seconds": c["nan"]["seconds"],
        "loss_mismatches": (len(c["sigkill"]["loss_mismatches"])
                            + len(c["torn"]["loss_mismatches"])),
        "nan_rollbacks": c["nan"]["rollbacks"],
        "preemption_final_ckpt": bool(
            c["preemption"]["final_checkpoint_steps"]),
    }
    return result


def check(result: dict) -> list:
    """-> list of violated invariants (empty = pass)."""
    bad = []
    if result["baseline"]["rc"] != 0:
        bad.append(f"baseline run failed rc="
                   f"{result['baseline']['rc']}")
    s = result["sigkill"]
    if s["crash_rc"] != -signal.SIGKILL:
        bad.append(f"sigkill child exited {s['crash_rc']}, not "
                   f"-SIGKILL — the crash never happened")
    if s["resume_rc"] != 0:
        bad.append(f"sigkill resume failed rc={s['resume_rc']}")
    if s["resumed_from_step"] is None or s["resumed_from_step"] < 1:
        bad.append(f"sigkill resume did not restore a checkpoint "
                   f"(start={s['resumed_from_step']})")
    if s["loss_mismatches"]:
        bad.append(f"SIGKILL resume broke bit-identity at batches "
                   f"{s['loss_mismatches']}")
    if s["recorded"] != result["steps"]:
        bad.append(f"sigkill phases recorded {s['recorded']}/"
                   f"{result['steps']} losses")
    if "resume" not in s["flight_classes"]:
        bad.append(f"no `resume` flight artifact after SIGKILL "
                   f"recovery (got {s['flight_classes']})")
    t = result["torn"]
    if t["torn_rc"] != 31:
        bad.append(f"torn-save child exited {t['torn_rc']}, not the "
                   f"fault's 31 — the mid-save crash never happened")
    if not t["torn_dirs_observed"]:
        bad.append("the mid-save crash left no torn (manifest-less) "
                   "checkpoint directory")
    if t["resume_rc"] != 0:
        bad.append(f"torn resume failed rc={t['resume_rc']}")
    if t["loss_mismatches"]:
        bad.append(f"torn fallback broke bit-identity at batches "
                   f"{t['loss_mismatches']}")
    if not t["fallback_logged"]:
        bad.append("torn fallback left no loud log line")
    if "ckpt_torn" not in t["flight_classes"]:
        bad.append(f"no `ckpt_torn` flight artifact (got "
                   f"{t['flight_classes']})")
    n = result["nan"]
    if n["rc"] != 0 or not n["completed"]:
        bad.append(f"NaN-burst run did not complete without human "
                   f"intervention (rc={n['rc']})")
    if not (1 <= n["rollbacks"] <= 2):
        bad.append(f"expected 1-2 rollbacks, got {n['rollbacks']}")
    if not n["final_loss_finite"]:
        bad.append("NaN-burst run ended with a non-finite loss")
    if "nonfinite_rollback" not in n["flight_classes"]:
        bad.append(f"no `nonfinite_rollback` flight artifact (got "
                   f"{n['flight_classes']})")
    if n["provenance"]["culprit"] != "feed/x":
        bad.append(f"provenance did not name the poisoned feed "
                   f"(culprit={n['provenance']['culprit']!r}, "
                   f"expected 'feed/x')")
    if n["provenance"]["trail_len"] < 1:
        bad.append("rollback artifact carries no numerics stats trail")
    if not n["surrendered"]:
        bad.append(f"all-NaN run did not surrender within the retry "
                   f"budget (rc={n['surrender_rc']})")
    if "recovery_surrender" not in n["surrender_flight"]:
        bad.append(f"no `recovery_surrender` flight artifact (got "
                   f"{n['surrender_flight']})")
    p = result["preemption"]
    if "preemption" not in p["flight_classes"]:
        bad.append(f"no `preemption` flight artifact after SIGTERM "
                   f"(got {p['flight_classes']})")
    if not p["final_checkpoint_steps"]:
        bad.append("SIGTERM left no final checkpoint")
    if p["rc"] != -signal.SIGTERM:
        bad.append(f"preempted worker exited {p['rc']}, not the "
                   f"standard -SIGTERM status")
    return bad


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--out", default="")
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--flight-dir", default="")
    ap.add_argument("--steps", type=int, default=STEPS)
    ap.add_argument("--crash-at", type=int, default=-1)
    ap.add_argument("--nan-at", default="")
    ap.add_argument("--recovery", action="store_true")
    ap.add_argument("--hang-after", type=int, default=-1)
    args = ap.parse_args(argv)
    if args.child:
        return child_main(args)
    result = measure(steps=args.steps)
    violations = check(result)
    result["violations"] = violations
    result["ok"] = not violations
    print(json.dumps(result, indent=2, default=str))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
