"""Decompose LM1B step wall time: device compute vs host/tunnel overhead.

Measures, on the live backend:
  A. pure device step rate: device-resident batch, no per-step fetch
  B. + per-step device_put of the host batch
  C. + per-step blocking scalar fetch (the session's current behavior)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import parallax_tpu as parallax
    from parallax_tpu.models import lm1b

    n = jax.device_count()
    platform = jax.devices()[0].platform
    cfg = (lm1b.LM1BConfig(num_partitions=n) if platform != "cpu"
           else lm1b.tiny_config(num_partitions=n))
    bs, T = (128 * n, 20) if platform != "cpu" else (16 * n, 8)
    sess, *_ = parallax.parallel_run(
        lm1b.build_model(cfg),
        parallax_config=parallax.Config(run_option="HYBRID",
                                        search_partitions=False))
    rng = np.random.default_rng(0)
    batches = [lm1b.make_batch(rng, bs, T, cfg.vocab_size)
               for _ in range(4)]
    for i in range(5):
        sess.run("loss", feed_dict=batches[i % 4])
    eng, state = sess.engine, sess.state
    dev_batches = [eng.shard_batch(b) for b in batches]
    jax.block_until_ready(state.params)
    N = 20

    # A: device-resident batches, fire-and-forget, block once
    t0 = time.perf_counter()
    for i in range(N):
        state, out = eng._step_jit(state, dev_batches[i % 4])
    jax.block_until_ready(state.params)
    a = (time.perf_counter() - t0) / N * 1e3

    # B: + device_put each step
    t0 = time.perf_counter()
    for i in range(N):
        state, out = eng._step_jit(state, eng.shard_batch(batches[i % 4]))
    jax.block_until_ready(state.params)
    b = (time.perf_counter() - t0) / N * 1e3

    # C: + blocking scalar fetch each step
    t0 = time.perf_counter()
    for i in range(N):
        state, out = eng._step_jit(state, eng.shard_batch(batches[i % 4]))
        float(np.asarray(out["words"]))
    jax.block_until_ready(state.params)
    c = (time.perf_counter() - t0) / N * 1e3

    # D: device_put cost alone
    t0 = time.perf_counter()
    for i in range(N):
        jax.block_until_ready(eng.shard_batch(batches[i % 4]))
    d = (time.perf_counter() - t0) / N * 1e3

    print(f"platform={platform}")
    print(f"A pure device step:        {a:7.1f} ms")
    print(f"B + device_put per step:   {b:7.1f} ms")
    print(f"C + blocking fetch:        {c:7.1f} ms")
    print(f"D device_put alone:        {d:7.1f} ms")
    sess.close()


if __name__ == "__main__":
    main()
