"""Serving SLO guard: no serve-time compiles, deadline discipline,
cheap batcher.

ISSUE 4 acceptance, enforced in tier-1
(tests/test_serve.py::test_serve_slo_guard) and runnable directly::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/check_serve_slo.py

Three contracts over a synthetic mixed-length CPU load
(tools/loadgen.py closed-loop clients, request lengths spread across
the declared length buckets):

* **zero serve-time recompiles** — the (batch x length) signature set
  is pre-registered and AOT-compiled at session construction;
  ``serve.recompiles`` (dispatches that missed the executable table)
  must read 0 across the whole run, with a ``jax.monitoring``
  backend-compile listener as the independent witness.
* **deadline discipline** — every accepted request either completes
  within its deadline or is CORRECTLY shed (``ServeOverloaded`` at
  admission / ``DeadlineExceeded`` before or during service); the
  overload phase (queue bound 4, deadlines shorter than the queue can
  drain) must actually exercise both shedding paths, and no request
  may complete AFTER its deadline.
* **batcher overhead <= 5% of step wall-time** — methodology of
  tools/check_obs_overhead.py: the batching layer adds a fixed set of
  host operations per dispatch (queue put/pop, batch formation:
  stack + pad + signature + executable lookup, result split,
  per-request bookkeeping), so the enforced number decomposes — each
  operation is unit-costed on a quiet thread (min over tight batches;
  minima are robust to contention) against the REAL request feeds, and
  the sum is divided by the median device step from the live load. The
  on-path measurement (``serve.batcher_overhead_ms``, which on a
  loaded box also absorbs GIL contention from the client threads) is
  reported for eyeballing, not asserted.
* **continuous-decode signature closure** (ISSUE 6) — the ENLARGED
  signature set of the paged/chunked/speculative decode path (page
  tables, every prefill chunk, draft step, verify step, insert against
  both fresh and stepped state) is AOT-warmed at construction; under a
  mixed-length decode load with retire/refill and page churn the
  ``jax.monitoring`` compile listener must stay at ZERO and every
  request must complete.
* **per-adapter conformance** (ISSUE 19) — EVERY registered
  DecodeProgram (``parallax_tpu.serve.registered_adapters``: the NMT
  encoder-decoder, the causal LM, the MoE-LM, the lm1b LSTM) serves a
  small mixed load with zero serve-time compiles and zero KV pages
  still mapped after drain — the closure/hygiene half of the
  model-agnostic contract (bit-identity lives in
  tests/test_adapters.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_compile_events = {"n": 0, "active": False}


def _install_listener():
    import jax

    def _listen(event, duration, **kw):
        if _compile_events["active"] and "backend_compile" in event:
            _compile_events["n"] += 1

    jax.monitoring.register_event_duration_secs_listener(_listen)


def _unit_cost_us(fn, iters: int = 500, batches: int = 7) -> float:
    best = float("inf")
    for _ in range(batches):
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e6


def _batcher_unit_costs(sess, make_feed) -> dict:
    """Unit-cost each host operation the batching layer adds per
    dispatch, against the session's REAL feed shapes (max_batch
    requests at the largest length bucket — the worst case)."""
    import numpy as np

    from parallax_tpu.compile import bucketing
    from parallax_tpu.serve.batcher import RequestQueue

    sc = sess._config.serve_config
    B = sc.max_batch
    # worst case: a full batch at the largest length bucket
    feed = sess._padded_example(sess._max_length_bucket())
    reqs = [sess._make_one_shot_request(feed, deadline=None)
            for _ in range(B)]
    stop = threading.Event()

    def form():
        batch = {}
        for name in reqs[0].feed:
            batch[name] = np.stack([r.feed[name] for r in reqs])
        return batch

    batch = form()
    sig = bucketing.batch_signature(batch)
    q = RequestQueue(max_queue=4 * B)

    def queue_roundtrip():
        for r in reqs:
            q.put(r)
        q.form_group(B, 0.0, stop)

    host = {"score": np.zeros((B,), np.float32)}

    def split():
        import jax.tree_util as jtu
        leaves, treedef = jtu.tree_flatten(host)
        batched = [np.ndim(a) >= 1 for a in leaves]
        for i in range(B):
            jtu.tree_unflatten(treedef,
                               [a[i] if s else a
                                for a, s in zip(leaves, batched)])

    hist = sess.metrics.histogram("serve.request_latency_ms")
    now = time.perf_counter()

    def bookkeeping():
        from parallax_tpu.obs import trace
        for r in reqs:
            hist.record(1.0)
            trace.record_span("serve.request", now - 1, now, id=r.id,
                              batch=B)

    return {
        "queue_roundtrip": round(_unit_cost_us(queue_roundtrip,
                                               iters=200), 3),
        "stack_pad": round(_unit_cost_us(form), 3),
        "batch_signature": round(_unit_cost_us(
            lambda: bucketing.batch_signature(batch)), 3),
        "executable_lookup": round(_unit_cost_us(
            lambda: sess._executables.get(sig)), 3),
        "result_split": round(_unit_cost_us(split), 3),
        "request_bookkeeping": round(_unit_cost_us(bookkeeping), 3),
    }


def measure(n_requests: int = 96, concurrency: int = 4,
            deadline_ms: float = 30000.0) -> dict:
    from tools import loadgen

    _install_listener()

    # -- phase 1: mixed-length load under a generous deadline ----------
    sess, make_feed = loadgen.demo_session()
    try:
        _compile_events["n"] = 0
        _compile_events["active"] = True
        report = loadgen.run_load(sess, make_feed, n_requests,
                                  concurrency=concurrency,
                                  deadline_ms=deadline_ms)
        _compile_events["active"] = False
        stats = sess.stats()
        unit_costs = _batcher_unit_costs(sess, make_feed)
    finally:
        sess.close()

    # -- phase 2: overload — admission must shed, deadlines must drop --
    import numpy as np

    import parallax_tpu as parallax
    from parallax_tpu.serve import ServeOverloaded

    over = parallax.Config(serve_config=parallax.ServeConfig(
        max_batch=2, max_wait_ms=20.0, max_queue=4))
    dim = 64
    sess2 = parallax.ServeSession(
        lambda p, b: {"y": (b["x"] @ p["w"]).mean(axis=(1, 2))},
        {"w": np.eye(dim, dtype=np.float32)},
        example_feed={"x": np.zeros((8, dim), np.float32)},
        config=over)
    burst = {"submitted": 0, "shed": 0, "accepted": []}
    try:
        _compile_events["active"] = True
        for _ in range(32):
            burst["submitted"] += 1
            try:
                burst["accepted"].append(sess2.submit(
                    {"x": np.zeros((8, dim), np.float32)},
                    deadline_ms=25.0))
            except ServeOverloaded:
                burst["shed"] += 1
        done = [0]
        timed_out = [0]
        late = [0]
        for r in burst["accepted"]:
            try:
                r.result(timeout=30.0)
                done[0] += 1
                if r.deadline is not None and r.t_done > r.deadline:
                    late[0] += 1
            except Exception:
                timed_out[0] += 1
        _compile_events["active"] = False
        stats2 = sess2.stats()
    finally:
        sess2.close()

    # -- phase 3: continuous decode over the ENLARGED signature set ----
    # paged KV + chunked prefill + speculative draft/verify, mixed
    # source lengths and mixed caps: retire/refill churn and page reuse
    # must dispatch AOT executables only
    dsess, dmake = loadgen.demo_decode_session(
        slots=8, T=12, Ts=8, page_size=4, model_dim=32,
        prefill_chunk_layers=1, spec_tokens=2)
    try:
        _compile_events["n"] = 0
        _compile_events["active"] = True
        decode_report = loadgen.run_load(dsess, dmake, 24,
                                         concurrency=8)
        _compile_events["active"] = False
        decode_compiles = _compile_events["n"]
        dstats = dsess.stats()
    finally:
        dsess.close()
    decode = {
        "completed": decode_report["completed"],
        "failed": decode_report["failed"],
        "tokens": decode_report["tokens"],
        "tokens_per_sec": decode_report["tokens_per_sec"],
        "ttft_ms": decode_report["ttft_ms"],
        "serve_time_xla_compiles": decode_compiles,
        "kv_pages_in_use_after": dstats.get("serve.kv_pages_in_use"),
        "kv_refill_deferred": dstats.get("serve.kv_refill_deferred", 0),
        "spec_accept_rate": dstats.get("serve.spec_accept_rate"),
        "prefill_chunks": dstats.get("serve.prefill_chunks"),
    }

    # -- phase 4: per-adapter conformance (ISSUE 19) -------------------
    # every registered DecodeProgram serves a small mixed load with
    # zero serve-time compiles (its per-adapter signature closure
    # held) and zero pages mapped after drain (retire/refill hygiene)
    from parallax_tpu.serve import registered_adapters

    adapters = {}
    for name, spec in sorted(registered_adapters().items()):
        prog, params = spec.build(paged=spec.paged, chunked=False)
        acfg = parallax.Config(serve_config=parallax.ServeConfig(
            max_batch=3, max_queue=64, prefix_cache=spec.paged))
        asess = parallax.ServeSession(program=prog, params=params,
                                      config=acfg)

        def afeed(i, _spec=spec):
            # fresh per-i generator: thread-safe and replayable
            return _spec.make_feed(np.random.default_rng(5000 + i))

        try:
            _compile_events["n"] = 0
            _compile_events["active"] = True
            arep = loadgen.run_load(asess, afeed, 9, concurrency=3,
                                    max_new_tokens=6)
            _compile_events["active"] = False
            a_compiles = _compile_events["n"]
            astats = asess.stats()
        finally:
            asess.close()
        adapters[name] = {
            "completed": arep["completed"],
            "failed": arep["failed"],
            "tokens": arep["tokens"],
            "serve_time_xla_compiles": a_compiles,
            "recompiles": astats.get("serve.recompiles", 0),
            # after close: retired pages transferred to the prefix
            # cache were released by the drain too
            "kv_pages_in_use_after":
                (asess.metrics.snapshot().get("serve.kv_pages_in_use")
                 if spec.paged else 0),
        }

    def _p50(h):
        return h["p50"] if isinstance(h, dict) else None

    step_p50 = _p50(stats.get("serve.step_ms"))
    batcher_p50 = _p50(stats.get("serve.batcher_overhead_ms"))
    added_us = sum(unit_costs.values())
    overhead = (added_us / (step_p50 * 1e3)
                if step_p50 else None)
    measured = (batcher_p50 / step_p50
                if step_p50 and batcher_p50 is not None else None)
    return {
        "load": report,
        "recompiles": (stats.get("serve.recompiles", 0)
                       + stats2.get("serve.recompiles", 0)),
        "serve_time_xla_compiles": _compile_events["n"],
        "step_ms_p50": step_p50,
        "added_us_per_batch": round(added_us, 2),
        "unit_costs_us": unit_costs,
        "overhead_frac": (round(overhead, 5)
                          if overhead is not None else None),
        # on-path measurement, contention included (informational —
        # see the module docstring)
        "onpath_batcher_ms_p50": batcher_p50,
        "onpath_overhead_frac": (round(measured, 5)
                                 if measured is not None else None),
        "batch_occupancy": stats.get("serve.batch_occupancy"),
        "decode": decode,
        "adapters": adapters,
        "burst": {
            "submitted": burst["submitted"],
            "shed": burst["shed"],
            "accepted": len(burst["accepted"]),
            "completed": done[0],
            "timed_out": timed_out[0],
            "completed_after_deadline": late[0],
        },
    }


def check(result: dict, max_overhead: float = 0.05) -> list:
    """-> list of violated invariants (empty = pass)."""
    bad = []
    load = result["load"]
    if result["recompiles"] != 0:
        bad.append(f"serve.recompiles = {result['recompiles']} "
                   f"(the AOT signature set leaked)")
    if result["serve_time_xla_compiles"] != 0:
        bad.append(f"{result['serve_time_xla_compiles']} XLA "
                   f"compile(s) fired during serving")
    if load["completed"] + load["shed"] + load["timeouts"] \
            != load["submitted"] or load["failed"]:
        bad.append(f"request accounting broken: {load}")
    if load["completed"] == 0:
        bad.append("no request completed under the SLO load")
    lat = load["latency_ms"]["max"]
    if lat is not None and lat > load["deadline_ms"]:
        bad.append(f"a request completed {lat}ms after submit, past "
                   f"its {load['deadline_ms']}ms deadline")
    b = result["burst"]
    if b["shed"] + b["timed_out"] == 0:
        bad.append("overload burst exercised neither shedding path "
                   f"(burst={b})")
    if b["completed_after_deadline"] != 0:
        bad.append(f"{b['completed_after_deadline']} burst request(s) "
                   f"completed AFTER their deadline instead of being "
                   f"shed")
    if b["completed"] + b["timed_out"] != b["accepted"]:
        bad.append(f"burst accounting broken: {b}")
    if result["overhead_frac"] is None:
        bad.append("no batcher/step timing recorded")
    elif result["overhead_frac"] > max_overhead:
        bad.append(f"batcher overhead {result['overhead_frac']} > "
                   f"{max_overhead} of step wall-time")
    d = result.get("decode") or {}
    if d.get("serve_time_xla_compiles", 0) != 0:
        bad.append(f"{d['serve_time_xla_compiles']} XLA compile(s) "
                   f"fired during continuous decode — the enlarged "
                   f"signature set (page tables / prefill chunks / "
                   f"draft+verify) leaked")
    if d.get("completed", 0) == 0 or d.get("failed", 0):
        bad.append(f"decode load did not complete cleanly: {d}")
    if d.get("kv_pages_in_use_after", 0) != 0:
        bad.append(f"{d['kv_pages_in_use_after']} KV page(s) leaked "
                   f"after all decode sequences retired")
    for name, a in sorted((result.get("adapters") or {}).items()):
        if a.get("recompiles", 0) != 0 \
                or a.get("serve_time_xla_compiles", 0) != 0:
            bad.append(f"adapter {name!r}: serve-time compile(s) "
                       f"fired (recompiles={a.get('recompiles')}, "
                       f"xla={a.get('serve_time_xla_compiles')}) — "
                       f"its signature closure leaked")
        if a.get("completed", 0) == 0 or a.get("failed", 0):
            bad.append(f"adapter {name!r} load did not complete "
                       f"cleanly: {a}")
        if a.get("kv_pages_in_use_after") not in (0, None):
            bad.append(f"adapter {name!r} leaked "
                       f"{a['kv_pages_in_use_after']} KV page(s) "
                       f"after drain")
    return bad


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=96)
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--max-overhead", type=float, default=0.05,
                    help="fail when the measured batcher cost exceeds "
                         "this fraction of step wall-time (default "
                         "0.05 = 5%%)")
    args = ap.parse_args(argv)
    result = measure(n_requests=args.requests,
                     concurrency=args.concurrency)
    violations = check(result, args.max_overhead)
    result["max_overhead"] = args.max_overhead
    result["violations"] = violations
    result["ok"] = not violations
    print(json.dumps(result, indent=2, default=str))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
