"""ResNet-50 throughput bench — the second named metric in
BASELINE.json ("ResNet-50 images/sec/chip"; reference driver logs
steps/sec + images/sec:
/root/reference/parallax/parallax/examples/tf_cnn_benchmarks/
CNNBenchmark_distributed_driver.py:85-91).

Writes perf/BENCH_RESNET_r05.json with the platform stamped, same
honesty contract as bench.py: a CPU fallback can never masquerade as a
TPU number. On TPU the realistic config is per-chip batch 64, v1.5,
bf16 batch; on CPU a tiny image/batch smoke keeps the artifact cheap
while still measuring the real engine path (dense AR, BatchNorm state
flow).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    import jax
    import numpy as np

    import parallax_tpu as parallax
    from parallax_tpu.models import cnn

    n_chips = jax.device_count()
    platform = jax.devices()[0].platform
    on_cpu = platform == "cpu"
    if on_cpu:
        name, size, bs, steps, warmup = "resnet50_v1.5", 64, 2 * n_chips, 6, 2
        classes = 100
    else:
        name, size, bs, steps, warmup = ("resnet50_v1.5", 224,
                                         64 * n_chips, 30, 5)
        classes = 1000

    model = cnn.build_model(name, num_classes=classes, image_size=size)
    sess, *_ = parallax.parallel_run(
        model, parallax_config=parallax.Config(run_option="AR",
                                               search_partitions=False))
    rng = np.random.default_rng(0)
    batches = [cnn.make_batch(rng, bs, size, classes) for _ in range(2)]
    for i in range(warmup):
        sess.run("loss", feed_dict=batches[i % 2])
    jax.block_until_ready(sess.state.params)
    t0 = time.perf_counter()
    for i in range(steps):
        sess.run([], feed_dict=batches[i % 2])
    jax.block_until_ready(sess.state.params)
    dt = time.perf_counter() - t0
    sess.close()

    result = {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(bs * steps / dt / n_chips, 2),
        "unit": "images/sec/chip",
        "steps_per_sec": round(steps / dt, 3),
        "platform": platform,
        "n_chips": n_chips,
        "model": name,
        "image_size": size,
        "global_batch": bs,
        "note": ("CPU smoke shapes (64px, tiny batch) — structure "
                 "only, not a throughput claim" if on_cpu else
                 "realistic per-chip batch 64 at 224px"),
    }
    line = json.dumps(result)
    print(line)
    out = os.path.join(os.path.dirname(__file__), "..", "perf",
                       "BENCH_RESNET_r05.json")
    with open(out, "w") as f:
        f.write(line + "\n")


if __name__ == "__main__":
    main()
