"""ResNet-50 throughput bench — the second named metric in
BASELINE.json ("ResNet-50 images/sec/chip"; reference driver logs
steps/sec + images/sec:
/root/reference/parallax/parallax/examples/tf_cnn_benchmarks/
CNNBenchmark_distributed_driver.py:85-91).

VERDICT r5 item 5: this number must TRACK — constant shapes round over
round so a 2× regression in the conv/BatchNorm path is caught like
LM1B's. The measured configuration is therefore fixed: **224 px,
ResNet-50 v1.5, 1000 classes, a constant per-chip batch** on every
platform (the old 64 px CPU "structure smoke" tracked nothing). Steps
are fewer on CPU, but the per-step work — the compiled program — is
shape-identical across rounds.

Each run writes ``perf/BENCH_RESNET_r<NN>.json`` (NN = next round) with
a ``harness`` block (shapes, steps, tool hash) and a ``vs_prev`` ratio
against the latest previous round whose harness is shape-compatible and
whose platform/chip-count match — the LM1B-style tracking number.
``vs_prev`` stays null (never fabricated) when the previous round is
missing, failed, or measured different shapes (e.g. every pre-r6
64 px artifact).

Same honesty contract as bench.py: the platform is stamped, so a CPU
fallback can never masquerade as a TPU number.
"""

import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_artifacts import load_block, round_number, \
    round_paths  # noqa: E402

PERF_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "..", "perf")

# The constant measured configuration (every round, every platform).
MODEL = "resnet50_v1.5"
IMAGE_SIZE = 224
CLASSES = 1000
PER_CHIP_BATCH = 2      # fixed: the tracked program's shape
# comparability requires identical compiled shapes; only the sample
# count differs by platform (CPU steps are expensive)
STEPS = {"cpu": 4, "default": 30}
WARMUP = {"cpu": 1, "default": 5}


def _tool_hash() -> str:
    try:
        with open(os.path.abspath(__file__), "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return "unknown"


def prev_rounds():
    """[(result dict, path), ...] newest first (unreadable rounds
    skipped) — vs_prev scans back to the latest COMPARABLE round, so
    one failed/incompatible round can't break the tracking number."""
    out = []
    for p in reversed(round_paths(PERF_DIR, "BENCH_RESNET_")):
        doc = load_block(p)
        if doc is not None:
            out.append((doc, p))
    return out


def next_round_path() -> str:
    paths = round_paths(PERF_DIR, "BENCH_RESNET_")
    nn = (round_number(paths[-1]) + 1) if paths else 1
    return os.path.join(PERF_DIR, "BENCH_RESNET_r%02d.json" % nn)


def vs_prev(result: dict, prev) -> tuple:
    """(ratio or None, why) — the LM1B-style round-over-round tracking
    number, computed only between shape-compatible measurements."""
    if not isinstance(prev, dict):
        return None, "no previous round artifact"
    if not isinstance(prev.get("value"), (int, float)) \
            or prev["value"] <= 0:
        return None, "previous round failed or has no value"
    for key in ("platform", "n_chips", "model", "image_size",
                "classes", "per_chip_batch"):
        if result.get(key) != prev.get(key):
            return None, (f"{key} differs ({prev.get(key)!r} -> "
                          f"{result.get(key)!r}); not comparable")
    return round(result["value"] / prev["value"], 4), "comparable"


def main():
    import jax
    import numpy as np

    import parallax_tpu as parallax
    from parallax_tpu.models import cnn

    n_chips = jax.device_count()
    platform = jax.devices()[0].platform
    key = "cpu" if platform == "cpu" else "default"
    steps = int(os.environ.get("PARALLAX_RESNET_STEPS",
                               STEPS[key]))
    warmup = int(os.environ.get("PARALLAX_RESNET_WARMUP",
                                WARMUP[key]))
    bs = PER_CHIP_BATCH * n_chips

    model = cnn.build_model(MODEL, num_classes=CLASSES,
                            image_size=IMAGE_SIZE)
    sess, *_ = parallax.parallel_run(
        model, parallax_config=parallax.Config(run_option="AR",
                                               search_partitions=False))
    rng = np.random.default_rng(0)
    batches = [cnn.make_batch(rng, bs, IMAGE_SIZE, CLASSES)
               for _ in range(2)]
    for i in range(warmup):
        sess.run("loss", feed_dict=batches[i % 2])
    jax.block_until_ready(sess.state.params)
    t0 = time.perf_counter()
    for i in range(steps):
        sess.run([], feed_dict=batches[i % 2])
    jax.block_until_ready(sess.state.params)
    dt = time.perf_counter() - t0
    goodput = sess.timeline.goodput()
    sess.close()

    result = {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(bs * steps / dt / n_chips, 3),
        "unit": "images/sec/chip",
        "steps_per_sec": round(steps / dt, 4),
        "platform": platform,
        "n_chips": n_chips,
        "model": MODEL,
        "image_size": IMAGE_SIZE,
        "classes": CLASSES,
        "per_chip_batch": PER_CHIP_BATCH,
        "global_batch": bs,
        # step-time attribution over the measured window (obs/timeline)
        "goodput": goodput,
        "harness": {
            "tool_sha256": _tool_hash(),
            "steps_measured": steps,
            "warmup_steps": warmup,
        },
        "note": ("constant tracked config: 224px v1.5, 1000 classes, "
                 f"{PER_CHIP_BATCH}/chip — comparable round-over-round "
                 "within one platform/chip-count"),
    }
    # scan back to the LATEST comparable round (a failed or
    # shape-incompatible round in between must not break tracking)
    ratio, why, prev_path = None, "no previous round artifact", None
    for i, (prev, path) in enumerate(prev_rounds()):
        r, w = vs_prev(result, prev)
        if i == 0:
            # nothing comparable at all -> report the LATEST round's
            # reason, not the oldest scanned
            why, prev_path = w, path
        if r is not None:
            ratio, why, prev_path = r, w, path
            break
    result["vs_prev"] = ratio
    result["vs_prev_basis"] = {
        "path": os.path.basename(prev_path) if prev_path else None,
        "why": why,
    }

    line = json.dumps(result)
    print(line)
    out = next_round_path()
    with open(out, "w") as f:
        f.write(line + "\n")
    print(f"# wrote {os.path.relpath(out)}", file=sys.stderr)


if __name__ == "__main__":
    main()
