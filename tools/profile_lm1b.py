"""Profile one LM1B hybrid train step on the live backend.

Captures a jax.profiler trace of a few steady-state steps and then
aggregates TPU op durations from the trace so the hotspot is readable
without TensorBoard. Usage:

    python tools/profile_lm1b.py [outdir]

Prints the top-20 ops by total self-duration on the device track.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_trace(outdir: str) -> None:
    import jax
    import numpy as np
    import parallax_tpu as parallax
    from parallax_tpu.models import lm1b

    n_chips = jax.device_count()
    platform = jax.devices()[0].platform
    mode = os.environ.get("PARALLAX_PROFILE_GRAD_MODE", "slices")
    if platform == "cpu":
        cfg = lm1b.tiny_config(num_partitions=n_chips,
                               sparse_grad_mode=mode)
        bs, T = 16 * n_chips, 8
    else:
        cfg = lm1b.LM1BConfig(num_partitions=n_chips,
                              sparse_grad_mode=mode)
        bs, T = 128 * n_chips, 20
    sess, *_ = parallax.parallel_run(
        lm1b.build_model(cfg),
        parallax_config=parallax.Config(run_option="HYBRID",
                                        search_partitions=False,
                                        sparse_grad_mode=mode))
    rng = np.random.default_rng(0)
    batches = [lm1b.make_batch(rng, bs, T, cfg.vocab_size)
               for _ in range(4)]
    for i in range(5):
        sess.run("loss", feed_dict=batches[i % 4])
    jax.block_until_ready(sess.state.params)
    with jax.profiler.trace(outdir):
        for i in range(8):
            sess.run("loss", feed_dict=batches[i % 4])
        jax.block_until_ready(sess.state.params)
    t0 = time.perf_counter()
    for i in range(10):
        sess.run("loss", feed_dict=batches[i % 4])
    jax.block_until_ready(sess.state.params)
    print(f"# step time (untraced): "
          f"{(time.perf_counter() - t0) / 10 * 1e3:.1f} ms "
          f"({platform}, bs={bs}, T={T})")
    sess.close()


def summarize(outdir: str, top: int = 25) -> None:
    paths = glob.glob(os.path.join(outdir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        print("no trace.json.gz found under", outdir)
        return
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # device tracks: pid whose process_name metadata mentions TPU/device;
    # fall back to aggregating every complete event by name.
    pid_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e["pid"]] = e["args"].get("name", "")
    device_pids = {p for p, n in pid_names.items()
                   if "TPU" in n or "/device" in n.lower()}
    totals, counts = {}, {}
    for e in events:
        if e.get("ph") != "X":
            continue
        if device_pids and e.get("pid") not in device_pids:
            continue
        name = e.get("name", "?")
        totals[name] = totals.get(name, 0.0) + e.get("dur", 0.0)
        counts[name] = counts.get(name, 0) + 1
    ranked = sorted(totals.items(), key=lambda kv: -kv[1])[:top]
    width = max((len(n) for n, _ in ranked), default=10)
    print(f"# device tracks: "
          f"{[pid_names[p] for p in device_pids] or 'ALL (no device pid)'}")
    for name, us in ranked:
        print(f"{name[:90]:<{min(width, 90)}}  "
              f"{us / 1e3:9.2f} ms  x{counts[name]}")


if __name__ == "__main__":
    outdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/lm1b_profile"
    run_trace(outdir)
    summarize(outdir)
