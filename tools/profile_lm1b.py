"""Profile one LM1B hybrid train step on the live backend.

Captures a jax.profiler trace of a few steady-state steps and
summarizes it through the shared ``obs/xprof`` parser (ONE owner for
trace parsing, ISSUE 13) so the hotspot is readable without
TensorBoard: top ops by self-duration with their taxonomy category,
the category split, and the coverage/residual account. Usage:

    python tools/profile_lm1b.py [outdir]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

TRACED_STEPS = 8


def run_trace(outdir: str):
    """Returns the compiled step's HLO index (obs/xprof) so the
    summary can join trace op names back to model scopes — the
    layer / dense-sparse / fwd-bwd attribution rows."""
    import jax
    import numpy as np
    import parallax_tpu as parallax
    from parallax_tpu.models import lm1b
    from parallax_tpu.obs import xprof

    n_chips = jax.device_count()
    platform = jax.devices()[0].platform
    mode = os.environ.get("PARALLAX_PROFILE_GRAD_MODE", "slices")
    # 'pallas' profiles the flagship's kernel-served recurrence
    # (ISSUE 14); default keeps the historical xla scan
    lstm_impl = os.environ.get("PARALLAX_PROFILE_LSTM_IMPL", "xla")
    if platform == "cpu":
        cfg = lm1b.tiny_config(num_partitions=n_chips,
                               sparse_grad_mode=mode,
                               lstm_impl=lstm_impl)
        bs, T = 16 * n_chips, 8
    else:
        cfg = lm1b.LM1BConfig(num_partitions=n_chips,
                              sparse_grad_mode=mode,
                              lstm_impl=lstm_impl)
        bs, T = 128 * n_chips, 20
    sess, *_ = parallax.parallel_run(
        lm1b.build_model(cfg),
        parallax_config=parallax.Config(run_option="HYBRID",
                                        search_partitions=False,
                                        sparse_grad_mode=mode))
    rng = np.random.default_rng(0)
    batches = [lm1b.make_batch(rng, bs, T, cfg.vocab_size)
               for _ in range(4)]
    for i in range(5):
        sess.run("loss", feed_dict=batches[i % 4])
    jax.block_until_ready(sess.state.params)
    with jax.profiler.trace(outdir):
        for i in range(TRACED_STEPS):
            sess.run("loss", feed_dict=batches[i % 4])
        jax.block_until_ready(sess.state.params)
    t0 = time.perf_counter()
    for i in range(10):
        sess.run("loss", feed_dict=batches[i % 4])
    jax.block_until_ready(sess.state.params)
    print(f"# step time (untraced): "
          f"{(time.perf_counter() - t0) / 10 * 1e3:.1f} ms "
          f"({platform}, bs={bs}, T={T}, lstm_impl={lstm_impl})")
    hlo_index = xprof.engine_hlo_index(sess.engine)
    sess.close()
    return hlo_index


def summarize(outdir: str, top: int = 25, hlo_index=None) -> None:
    """Shared-parser summary (obs/xprof): top ops by SELF duration
    (nesting resolved, unlike the old inline aggregation that counted
    a while loop and its body twice), the category split, the
    coverage/residual account, and — with an ``hlo_index`` — the
    forward/backward attribution row (ISSUE 14: where the training
    step's backward actually goes) plus the per-op LSTM rows."""
    from parallax_tpu.obs import xprof

    try:
        trace, path = xprof.load_trace(outdir)
    except FileNotFoundError:
        print("no trace.json(.gz) found under", outdir)
        return
    attrib = xprof.attribute(trace, steps=TRACED_STEPS, top=top,
                             hlo_index=hlo_index, source=path)
    print(f"# {attrib.events} device op event(s) on {attrib.tracks} "
          f"track(s) [{attrib.track_basis}]")
    if attrib.coverage is not None:
        print(f"# device step wall {attrib.wall_ms:.2f} ms, "
              f"attributed {attrib.attributed_ms:.2f} ms "
              f"({attrib.coverage * 100:.1f}%), residual "
              f"{attrib.residual_ms:.2f} ms")
    for cat, row in attrib.by_category.items():
        print(f"# {cat:<11} {row['self_ms']:9.2f} ms  "
              f"share {row['share']:.3f}  x{row['events']}")
    # backward-attribution row (ISSUE 14): fwd-vs-bwd self-time from
    # the HLO op_name transpose(...) scopes; all-unmapped when no
    # hlo_index was joinable (visible, never fabricated)
    fb = attrib.fwd_bwd or {}
    total = sum(fb.values()) or 1.0
    print("# fwd/bwd     "
          + "  ".join(f"{k.replace('_self_ms', '')} "
                      f"{v:.2f} ms ({v / total:.0%})"
                      for k, v in fb.items()))
    lstm_layers = {k: v for k, v in attrib.layers.items()
                   if "lstm" in k.lower()}
    for layer, v in lstm_layers.items():
        print(f"# lstm layer  {layer:<40} {v:9.2f} ms")
    width = max((len(r["op"]) for r in attrib.top_ops), default=10)
    for r in attrib.top_ops:
        print(f"{r['op'][:90]:<{min(width, 90)}}  "
              f"{r['self_ms']:9.2f} ms  x{r['count']:<5} "
              f"[{r['category']}]")


if __name__ == "__main__":
    outdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/lm1b_profile"
    index = run_trace(outdir)
    summarize(outdir, hlo_index=index)
