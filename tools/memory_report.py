"""Per-device HBM accounting for the flagship configuration.

Three layers of evidence (committed under perf/ per ROADMAP item 12;
the third added by ISSUE 13):

1. **State bytes, exact, from the sharding plan** (abstract eval — no
   allocation): params / optimizer state / slice-adagrad accumulators,
   per device, split replicated vs sharded. This is where the hybrid
   design pays off — the 793k-vocab tables and their accumulators are
   row-sharded while the LSTM stack is replicated.
2. **Compiled-step memory analysis** (XLA `memory_analysis()` on the
   jitted training step, through the shared
   ``obs/memwatch.compiled_memory`` helper — one owner for the field
   set and the derived peak): activation/temp footprint the compiler
   actually schedules, argument/output aliasing included. Compiling the
   full flagship on the CPU emulator is expensive, so this layer runs
   on a scaled config by default (`--compile_scale`) and on the real
   one with `--compile_scale 1`.
3. **Runtime-measured live peak** (``obs/memwatch.MemWatch`` sampling
   ``device_memory_stats`` across real executed steps): what the
   allocator actually held, next to what the plan says it should and
   what the compiler scheduled. Honest on the CPU rig: XLA:CPU
   reports no memory stats, so this layer records ``unavailable``
   there instead of a fabricated number — it goes live on TPU capture.

Run: python tools/memory_report.py [--out perf/MEMORY_r04.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count"
                                 "=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _per_device_bytes(tree, mesh):
    """(replicated_bytes, sharded_bytes) one device holds for a pytree
    of arrays/ShapeDtypeStructs with known shardings."""
    import jax
    import numpy as np

    n = mesh.devices.size
    repl = sharded = 0
    for leaf in jax.tree.leaves(tree):
        if not hasattr(leaf, "shape"):
            continue
        total = int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize
        sharding = getattr(leaf, "sharding", None)
        if sharding is None or sharding.is_fully_replicated:
            repl += total
        else:
            shard_elems = int(np.prod(
                sharding.shard_shape(leaf.shape) or (1,)))
            sharded += shard_elems * leaf.dtype.itemsize
    return repl, sharded


def state_accounting(n_chips=8, batch_per_chip=128, num_steps=20,
                     table_dtype="float32"):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from parallax_tpu.common.config import ParallaxConfig
    from parallax_tpu.core import engine as engine_lib, mesh as mesh_lib
    from parallax_tpu.models import lm1b

    mesh = mesh_lib.build_mesh(jax.devices()[:n_chips],
                               num_partitions=n_chips)
    cfg = lm1b.LM1BConfig(num_partitions=n_chips,
                          sparse_grad_mode="slices",
                          table_dtype=jnp.dtype(table_dtype))
    model = lm1b.build_model(cfg)
    batch = lm1b.make_batch(np.random.default_rng(0),
                            batch_per_chip * n_chips, num_steps,
                            cfg.vocab_size)
    config = ParallaxConfig(run_option="HYBRID", search_partitions=False,
                            sparse_grad_mode="slices")
    eng = engine_lib.Engine(model, mesh, config, batch)
    # eval_shape drops the plan's shardings; compiling init (no
    # execution, no allocation) exposes them via output_shardings
    shapes = jax.eval_shape(eng._init_jit, 0)
    shardings = eng._init_jit.lower(0).compile().output_shardings
    state = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                           sharding=sh),
        shapes, shardings)

    out = {}
    for name, tree in (("params", state.params),
                       ("opt_state", state.opt_state),
                       ("slice_state", state.slice_state)):
        repl, shard = _per_device_bytes(tree, mesh)
        out[name] = {"replicated_bytes": repl, "sharded_bytes": shard,
                     "per_device_bytes": repl + shard}
    parts = list(out.values())
    out["total_per_device_bytes"] = sum(
        v["per_device_bytes"] for v in parts)
    # what a pure-replication design (the reference's MPI mode) would
    # hold per device: every sharded plane times the shard count
    n = mesh.devices.size
    out["replicated_design_per_device_bytes"] = sum(
        v["replicated_bytes"] + v["sharded_bytes"] * n for v in parts)
    return out


def compiled_accounting(n_chips=8, scale=8):
    """memory_analysis() of the compiled hybrid step on a 1/scale-vocab
    config (the full flagship compiles too slowly on the CPU emulator
    for routine runs)."""
    import jax
    import numpy as np

    from parallax_tpu.common.config import ParallaxConfig
    from parallax_tpu.core import engine as engine_lib, mesh as mesh_lib
    from parallax_tpu.models import lm1b

    mesh = mesh_lib.build_mesh(jax.devices()[:n_chips],
                               num_partitions=n_chips)
    cfg = lm1b.LM1BConfig(vocab_size=793470 // scale,
                          num_samples=8192 // scale,
                          num_partitions=n_chips,
                          sparse_grad_mode="slices")
    model = lm1b.build_model(cfg)
    batch = lm1b.make_batch(np.random.default_rng(0), 128 * n_chips,
                            20, cfg.vocab_size)
    config = ParallaxConfig(run_option="HYBRID", search_partitions=False,
                            sparse_grad_mode="slices")
    eng = engine_lib.Engine(model, mesh, config, batch)
    state = jax.eval_shape(eng._init_jit, 0)
    placed = eng.shard_batch(batch)
    abstract_batch = {
        k: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=v.sharding)
        for k, v in placed.items()}
    with eng.mesh:
        compiled = eng._step_jit.lower(state, abstract_batch).compile()
    # the shared field set + derived peak (obs/memwatch.py) — the same
    # numbers the tuner's OOM preflight judges
    from parallax_tpu.obs import memwatch
    mem = memwatch.compiled_memory(compiled)
    if mem is None:
        raise RuntimeError("memory_analysis unavailable on this "
                           "backend")
    return {"vocab_scale": scale, **mem}


def runtime_accounting(n_chips=8, scale=8, steps=5):
    """Third evidence layer: live allocator peak across real executed
    steps of the scaled config (obs/memwatch ring over
    device_memory_stats). ``unavailable`` — honestly — on backends
    without memory stats (XLA:CPU)."""
    import jax
    import numpy as np

    from parallax_tpu.common.config import ParallaxConfig
    from parallax_tpu.core import engine as engine_lib, mesh as mesh_lib
    from parallax_tpu.models import lm1b
    from parallax_tpu.obs.memwatch import MemWatch

    mesh = mesh_lib.build_mesh(jax.devices()[:n_chips],
                               num_partitions=n_chips)
    cfg = lm1b.LM1BConfig(vocab_size=793470 // scale,
                          num_samples=8192 // scale,
                          num_partitions=n_chips,
                          sparse_grad_mode="slices")
    model = lm1b.build_model(cfg)
    batch = lm1b.make_batch(np.random.default_rng(0), 128 * n_chips,
                            20, cfg.vocab_size)
    config = ParallaxConfig(run_option="HYBRID", search_partitions=False,
                            sparse_grad_mode="slices")
    eng = engine_lib.Engine(model, mesh, config, batch)
    state = eng.init_state(0)
    mw = MemWatch()
    for step in range(steps):
        state, _ = eng.step(state, batch)
        jax.block_until_ready(state.params)
        mw.sample(step)
    peak = mw.live_peak_bytes()
    return {
        "vocab_scale": scale, "steps": steps,
        "live_peak_bytes": peak,
        "note": (None if peak else
                 "backend reports no device memory stats "
                 "(XLA:CPU); goes live on TPU capture"),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--n_chips", type=int, default=8)
    ap.add_argument("--compile_scale", type=int, default=8)
    args = ap.parse_args()
    result = {
        "state_fp32_tables": state_accounting(args.n_chips),
        "state_bf16_tables": state_accounting(args.n_chips,
                                              table_dtype="bfloat16"),
    }
    try:
        result["compiled_step"] = compiled_accounting(
            args.n_chips, args.compile_scale)
    except Exception as e:  # memory_analysis availability varies
        result["compiled_step"] = {"error": str(e)[:300]}
    try:
        result["measured_runtime"] = runtime_accounting(
            args.n_chips, args.compile_scale)
    except Exception as e:
        result["measured_runtime"] = {"error": str(e)[:300]}
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
