"""Shared helpers for per-round bench artifacts.

One implementation of the two conventions every bench consumer needs
(bench.py's previous-round loader, tools/check_regression.py's gate,
tools/bench_resnet.py's tracking number), so a change to the artifact
layout happens in one place:

* **round files** — ``<PREFIX>r<NN>.json``, ordered by round NUMBER
  (a lexical sort would put r10 before r9);
* **the driver wrapper** — repo-root artifacts arrive as
  ``{"n": ..., "rc": ..., "tail": "...", "parsed": {<the bench JSON
  line>}}``; tools must accept both the wrapper and the raw line.

The wrapper's ``parsed`` block has been observed TRUNCATED (r05: it
carried the headline keys but dropped ``harness`` — so the r5->r6 gate
could not replay an ``ab_vs_prev_harness`` A/B and reported
``not_comparable``). ``load_block`` therefore recovers: when the
wrapper also carries the raw stdout ``tail``, the last JSON result
line found there backfills any top-level key the ``parsed`` block
lost (``parsed`` values win on conflict). A wrapper whose tail was
itself truncated past the JSON line recovers nothing — but the
harness params survive the wrapper whenever the bytes survive at all.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import List, Optional, Tuple


def round_number(path: str) -> int:
    """The NN of a ``..._rNN.json`` path, or -1 when it has none."""
    m = re.search(r"_r(\d+)\.json$", path)
    return int(m.group(1)) if m else -1


def round_paths(directory: str, prefix: str = "BENCH_") -> List[str]:
    """Every ``<prefix>r<NN>.json`` in ``directory``, ascending by
    round number."""
    paths = glob.glob(os.path.join(directory, prefix + "r*.json"))
    return sorted((p for p in paths if round_number(p) >= 0),
                  key=round_number)


def _result_lines_from_tail(tail: str) -> List[dict]:
    """Every line of captured stdout that parses as a bench result
    object (a dict carrying ``value``), in order."""
    out = []
    for line in tail.splitlines():
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "value" in obj:
            out.append(obj)
    return out


def load_block(path: str) -> Optional[dict]:
    """The bench result block from ``path`` — unwraps the driver
    format; None when unreadable or structurally not a result.

    A wrapper whose ``parsed`` block was truncated (module docstring)
    is REPAIRED from the wrapper's own ``tail``: the last raw result
    line found there backfills any missing top-level key — notably
    ``harness``, which the regression gate and the ``ab_vs_prev_
    harness`` replay cannot work without. ``parsed`` values win on
    conflict (the driver parsed them deliberately)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and "value" in parsed:
        tail = doc.get("tail")
        if isinstance(tail, str):
            lines = _result_lines_from_tail(tail)
            if lines:
                raw = lines[-1]
                # backfill unless the tail line measured a DIFFERENT
                # metric; a parsed block truncated past its own
                # "metric" key is exactly the case that needs repair
                pm = parsed.get("metric")
                if pm is None or raw.get("metric") == pm:
                    recovered = dict(raw)
                    recovered.update(parsed)
                    return recovered
        return parsed
    return doc if "value" in doc else None


def latest_rounds(directory: str, prefix: str = "BENCH_"
                  ) -> Tuple[Optional[str], Optional[str]]:
    """(current, previous) paths by round number; None when absent."""
    paths = round_paths(directory, prefix)
    if not paths:
        return None, None
    if len(paths) == 1:
        return paths[0], None
    return paths[-1], paths[-2]
