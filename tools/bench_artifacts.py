"""Shared helpers for per-round bench artifacts.

One implementation of the two conventions every bench consumer needs
(bench.py's previous-round loader, tools/check_regression.py's gate,
tools/bench_resnet.py's tracking number), so a change to the artifact
layout happens in one place:

* **round files** — ``<PREFIX>r<NN>.json``, ordered by round NUMBER
  (a lexical sort would put r10 before r9);
* **the driver wrapper** — repo-root artifacts arrive as
  ``{"n": ..., "rc": ..., "parsed": {<the bench JSON line>}}``; tools
  must accept both the wrapper and the raw line.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import List, Optional, Tuple


def round_number(path: str) -> int:
    """The NN of a ``..._rNN.json`` path, or -1 when it has none."""
    m = re.search(r"_r(\d+)\.json$", path)
    return int(m.group(1)) if m else -1


def round_paths(directory: str, prefix: str = "BENCH_") -> List[str]:
    """Every ``<prefix>r<NN>.json`` in ``directory``, ascending by
    round number."""
    paths = glob.glob(os.path.join(directory, prefix + "r*.json"))
    return sorted((p for p in paths if round_number(p) >= 0),
                  key=round_number)


def load_block(path: str) -> Optional[dict]:
    """The bench result block from ``path`` — unwraps the driver
    format; None when unreadable or structurally not a result."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and "value" in parsed:
        return parsed
    return doc if "value" in doc else None


def latest_rounds(directory: str, prefix: str = "BENCH_"
                  ) -> Tuple[Optional[str], Optional[str]]:
    """(current, previous) paths by round number; None when absent."""
    paths = round_paths(directory, prefix)
    if not paths:
        return None, None
    if len(paths) == 1:
        return paths[0], None
    return paths[-1], paths[-2]
