"""Reconstruct "what happened to this run" from its ops artifacts.

Joins the run's event journal (``Config(journal_path=...)`` JSONL)
with a goodput account (``session.ops_account()`` JSON, or the ``ops``
section of any flight dump) into one operator-facing report::

    python tools/ops_report.py --journal run/journal.jsonl \
        [--account run/account.json | --flight run/flight_xxx.json] \
        [--json]

The report answers the three questions an on-call asks first:

* **what happened** — the causal event timeline (attempts delimited by
  seq restarts; severity-tagged; incident ids shown so a line can be
  joined with its flight artifact);
* **where did the time go** — the goodput fraction and the badput
  breakdown, naming the DOMINANT badput class (the one worth fixing
  first);
* **what is still wrong** — alert firings without a matching resolve.

``--json`` emits the same content machine-readable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from parallax_tpu.obs.goodput import BADPUT_CLASSES, dominant_badput  # noqa: E402
from parallax_tpu.obs.journal import read_journal  # noqa: E402


def _load_account(args) -> dict:
    if args.account:
        with open(args.account) as f:
            doc = json.load(f)
        # accept either a bare account or the check_goodput child doc
        return doc.get("account", doc)
    if args.flight:
        with open(args.flight) as f:
            doc = json.load(f)
        return ((doc.get("sections") or {}).get("ops")
                or doc.get("ops") or {})
    return {}


def _attempts(events: list) -> list:
    """Split the event stream at seq restarts: each process emits its
    own monotonic seq, so a drop back to a lower seq marks a new
    attempt (the resume appended to the same file)."""
    attempts: list = []
    last_seq = None
    for e in events:
        seq = e.get("seq", 0)
        if last_seq is None or seq <= last_seq and seq == 1:
            attempts.append([])
        last_seq = seq
        if not attempts:
            attempts.append([])
        attempts[-1].append(e)
    return attempts


def _unresolved_alerts(events: list) -> list:
    firing: dict = {}
    for e in events:
        if e.get("subsystem") != "alert":
            continue
        name = (e.get("fields") or {}).get("alert")
        if e.get("kind") == "firing":
            firing[name] = e
        elif e.get("kind") == "resolved":
            firing.pop(name, None)
    return sorted(firing)


def build_report(events: list, account: dict) -> dict:
    attempts = _attempts(events)
    severities = {"error": 0, "warning": 0, "info": 0, "debug": 0}
    incidents = []
    for e in events:
        severities[e.get("severity", "info")] = \
            severities.get(e.get("severity", "info"), 0) + 1
        if e.get("incident_id"):
            incidents.append(e["incident_id"])
    badput = dict(account.get("badput_s") or {})
    report = {
        "events": len(events),
        "attempts_in_journal": len(attempts),
        "severities": severities,
        "incident_ids": sorted(set(incidents)),
        "unresolved_alerts": _unresolved_alerts(events),
        "account": {
            "wall_s": account.get("wall_s"),
            "goodput_fraction": account.get("goodput_fraction"),
            "steps": account.get("steps"),
            "attempts": account.get("attempts"),
            "badput_s": badput,
        } if account else None,
        "dominant_badput": (dominant_badput(account)
                            if account else None),
    }
    return report


def _fmt_ts(ts) -> str:
    try:
        return time.strftime("%H:%M:%S", time.localtime(float(ts))) \
            + f".{int((float(ts) % 1) * 1000):03d}"
    except (TypeError, ValueError):
        return "?"


def render_text(events: list, account: dict, report: dict,
                last: int = 40) -> str:
    lines = []
    w = lines.append
    w("== run timeline "
      f"({report['events']} events, "
      f"{report['attempts_in_journal']} attempt(s) in journal) ==")
    shown = events[-last:]
    if len(events) > len(shown):
        w(f"   ... {len(events) - len(shown)} earlier events elided "
          f"(--last to widen)")
    for e in shown:
        sev = e.get("severity", "info")
        mark = {"error": "!!", "warning": " !"}.get(sev, "  ")
        extra = ""
        if e.get("incident_id"):
            extra += f" incident={e['incident_id']}"
        fields = e.get("fields") or {}
        if fields:
            kv = " ".join(f"{k}={v}" for k, v in list(fields.items())[:5])
            extra += f" [{kv}]"
        w(f"{mark} {_fmt_ts(e.get('ts'))} {e.get('subsystem')}/"
          f"{e.get('kind')}{extra}")
    if report["account"]:
        a = report["account"]
        w("")
        w("== where the wall clock went ==")
        w(f"   wall {a['wall_s']}s over {a['attempts']} attempt(s), "
          f"{a['steps']} steps, goodput {a['goodput_fraction']}")
        for cls in BADPUT_CLASSES + ("unattributed",):
            v = (a["badput_s"] or {}).get(cls)
            if v:
                star = " <-- dominant" \
                    if cls == report["dominant_badput"] else ""
                w(f"   badput {cls:<20} {v:>10.3f}s{star}")
        if report["dominant_badput"] is None:
            w("   no badput recorded")
    w("")
    if report["unresolved_alerts"]:
        w(f"== STILL FIRING: {', '.join(report['unresolved_alerts'])} ==")
    else:
        w("== no unresolved alerts ==")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--journal", required=True,
                    help="journal JSONL path (Config(journal_path=...))")
    ap.add_argument("--account", default="",
                    help="ops account JSON (session.ops_account())")
    ap.add_argument("--flight", default="",
                    help="flight dump JSON (its `ops` section is used)")
    ap.add_argument("--last", type=int, default=40,
                    help="timeline events to show (default 40)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)
    events = read_journal(args.journal)
    if not events:
        print(f"no events readable from {args.journal}",
              file=sys.stderr)
        return 1
    account = _load_account(args)
    report = build_report(events, account)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(render_text(events, account, report, last=args.last))
    return 0


if __name__ == "__main__":
    sys.exit(main())
