"""Bench regression gate: fail on unexplained headline moves.

VERDICT r5: the r4→r5 headline moved −23% and "no artifact explains
it"; nothing would catch a 2× regression round-over-round. This tool
turns that into a gated check: compare the current ``BENCH_r*.json``
against the previous round's block and FAIL (exit 1) when the headline
moved more than ``--max-drop`` between *harness-compatible* rounds
without an in-artifact explanation.

Comparability — a delta is only attributable when the two rounds
measured the same thing the same way:

  * same ``metric`` and ``platform`` and ``n_chips`` (a CPU-fallback
    round can never gate a TPU round, and vice versa);
  * same ``bench_version``; a version bump is a declared methodology
    change and is judged EXPLAINED iff the current artifact carries the
    ``ab_vs_prev_harness`` A/B block (bench.py records it
    automatically on a bump) — the block shows what part of the move
    the methodology accounts for;
  * when both rounds carry ``harness.bench_sha256``, the hashes must
    match (same version but an edited harness file is an undeclared
    methodology change → not comparable, reported as such).

Explanations accepted for an over-threshold move between comparable
rounds: a ``regression_note`` string in the current artifact (a human
wrote down why). Anything else over the threshold fails.

**Secondary gates** (ISSUE 6): between harness-compatible rounds the
``serve``, ``decode``, ``ckpt`` and ``tune`` blocks are gated the same
way the training headline is — one-shot QPS, continuous-decode
tokens/sec and TTFT, the cached-decode latency row, checkpoint
save/restore latency, and the auto-tuner's search seconds and
predicted-over-measured drift must not regress unexplained. A
gate whose value is missing on either side is SKIPPED (reported), so
adding a new sub-block never fails the round that introduces it; the
global ``regression_note`` explains secondary moves too.

Artifacts are accepted in both layouts: the driver wrapper
(``{"parsed": {...}}``, what lands in the repo root) and the raw
bench.py JSON line. Failed rounds (``value`` 0 / ``error`` set) never
gate — there is nothing to compare.

Usage::

    python tools/check_regression.py                  # two latest BENCH_r*.json
    python tools/check_regression.py CUR.json PREV.json --max-drop 0.15

Exit 0: ok / explained / not comparable (reported); exit 1: unexplained
regression; exit 2: nothing usable to compare (missing/unreadable/
failed artifacts) — the gate fails CLOSED rather than showing green
over data it never measured.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_artifacts import latest_rounds, load_block  # noqa: E402

DEFAULT_MAX_DROP = 0.15   # fail on >15% unexplained headline drop
DEFAULT_MAX_RISE = 0.50   # >50% unexplained rise is *flagged* (exit 0)

# shared round-file/driver-wrapper conventions (tools/bench_artifacts)
load_bench = load_block
discover_rounds = latest_rounds


def _usable(block: Optional[dict]) -> bool:
    return (isinstance(block, dict)
            and isinstance(block.get("value"), (int, float))
            and block["value"] > 0
            and not block.get("error"))


def compare(current: Optional[dict], previous: Optional[dict],
            max_drop: float = DEFAULT_MAX_DROP,
            max_rise: float = DEFAULT_MAX_RISE) -> dict:
    """The gate verdict. ``status``:

    * ``ok``              — comparable, move within bounds
    * ``explained``       — over-threshold but explained in-artifact
    * ``regression``      — unexplained drop beyond ``max_drop`` (FAIL)
    * ``suspicious_rise`` — unexplained rise beyond ``max_rise``
      (flagged, passes: faster is not a failure, but an unexplained 2×
      "win" usually means the harness broke)
    * ``not_comparable``  — rounds measured different things (reported)
    * ``no_data``         — fewer than two usable artifacts
    """
    out = {"max_drop": max_drop, "max_rise": max_rise}
    if not _usable(current) or not _usable(previous):
        out["status"] = "no_data"
        out["why"] = ("current round unusable" if not _usable(current)
                      else "previous round unusable (failed or missing)")
        return out
    out["current_value"] = current["value"]
    out["previous_value"] = previous["value"]
    ratio = current["value"] / previous["value"]
    out["ratio"] = round(ratio, 4)
    out["delta_pct"] = round((ratio - 1.0) * 100, 2)

    for key in ("metric", "platform", "n_chips"):
        if current.get(key) != previous.get(key):
            out["status"] = "not_comparable"
            out["why"] = (f"{key} differs: {current.get(key)!r} vs "
                          f"{previous.get(key)!r}")
            return out
    if current.get("bench_version") != previous.get("bench_version"):
        bump = (f"bench_version bumped "
                f"{previous.get('bench_version')} -> "
                f"{current.get('bench_version')}")
        ab = current.get("ab_vs_prev_harness")
        v_ab = (ab.get("value_under_prev_params")
                if isinstance(ab, dict) else None)
        if not isinstance(v_ab, (int, float)) or v_ab <= 0:
            out["status"] = "not_comparable"
            out["why"] = (f"{bump} with no usable ab_vs_prev_harness "
                          "A/B block — the methodology move is "
                          "unexplained in-artifact")
            return out
        # the A/B IS the apples-to-apples number: the current build
        # measured under the previous round's harness params. The gate
        # judges THAT ratio — a version bump must not amnesty a build
        # regression the A/B itself exposes.
        out["ab_vs_prev_harness"] = ab
        ab_ratio = v_ab / previous["value"]
        out["ab_ratio"] = round(ab_ratio, 4)
        if ab_ratio < 1.0 - max_drop:
            note = current.get("regression_note")
            if note:
                out["status"] = "explained"
                out["why"] = (f"{bump}; A/B under prev params dropped "
                              f"{round((ab_ratio - 1) * 100, 2)}% but "
                              f"regression_note: {note}")
            else:
                out["status"] = "regression"
                out["why"] = (
                    f"{bump}, and the same-build A/B under the "
                    f"PREVIOUS round's harness params still dropped "
                    f"{round((1 - ab_ratio) * 100, 2)}% (> "
                    f"{max_drop * 100:.0f}%) — the move is the "
                    f"build's, not the methodology's")
        else:
            out["status"] = "explained"
            out["why"] = (f"{bump}; the same-build A/B under the "
                          f"previous harness params moved only "
                          f"{round((ab_ratio - 1) * 100, 2)}% — the "
                          f"headline delta is methodology")
        return out
    cur_sha = (current.get("harness") or {}).get("bench_sha256")
    prev_sha = (previous.get("harness") or {}).get("bench_sha256")
    if cur_sha and prev_sha and cur_sha != prev_sha:
        out["status"] = "not_comparable"
        out["why"] = ("harness hash changed within bench_version "
                      f"{current.get('bench_version')} ({prev_sha} -> "
                      f"{cur_sha}): an undeclared methodology change")
        return out
    out["harness_verified"] = bool(cur_sha and prev_sha)

    if ratio < 1.0 - max_drop:
        note = current.get("regression_note")
        if note:
            out["status"] = "explained"
            out["why"] = f"regression_note: {note}"
        else:
            out["status"] = "regression"
            out["why"] = (f"headline dropped {out['delta_pct']}% "
                          f"(> {max_drop * 100:.0f}%) between "
                          "harness-compatible rounds with no "
                          "explanation in-artifact")
        return out
    if ratio > 1.0 + max_rise:
        out["status"] = "suspicious_rise"
        out["why"] = (f"headline rose {out['delta_pct']}% — not a "
                      "failure, but verify the harness still measures "
                      "the same work")
        return out
    out["status"] = "ok"
    out["why"] = f"move {out['delta_pct']}% within bounds"
    return out


# (dotted path, higher_is_better) — a negative list index addresses
# from the end (the decode rows' largest target length)
SECONDARY_GATES = (
    ("serve.qps", True),
    ("serve.latency_ms.p50", False),
    ("serve.continuous.tokens_per_sec_best", True),
    ("serve.continuous.ttft_ms_p50_at_8x", False),
    ("decode.rows.-1.cached_ms", False),
    ("decode.spec_vs_plain.tokens_per_sec_spec", True),
    ("decode.paged_vs_dense.paged_step_ms", False),
    # p99 attribution (ISSUE 12, tools/serve_report via the sweep's
    # 64-offered row): the tail latency the request-trace layer
    # decomposes must not quietly regress — both the p99 TTFT and the
    # p99 total latency of the attribution report are gated (the
    # dominant-cause LABEL is diagnostic, not gateable; missing-on-
    # either-side keys skip, per the established convention)
    ("serve.continuous.report.buckets.p99.ttft_ms", False),
    ("serve.continuous.report.buckets.p99.total_ms", False),
    # prefix-aware KV reuse (ISSUE 15, bench "serve.prefix" block from
    # tools/check_prefix_reuse.py): the warm-path TTFT is THE number
    # prefix reuse exists to buy — a rise means replay/COW/eviction
    # overhead crept in — and the hit rate at the fixed 50%-shared
    # load dropping means the radix index stopped matching what it
    # used to (keying or eviction drift, not workload drift: the
    # request stream is deterministic)
    ("serve.prefix.ttft_ms_p50_warm", False),
    ("serve.prefix.hit_rate", True),
    # fleet robustness latencies (ISSUE 7, tools/check_fleet_faults):
    # how long a crash's failed-over requests take to land on healthy
    # replicas, and the longest fleet-wide completion gap during a
    # rotating weight hot-swap — both must not quietly regress
    ("serve.fleet.failover_recovery_ms", False),
    ("serve.fleet.hotswap_blackout_ms", False),
    # checkpoint costs (ISSUE 9, tools/bench_ckpt): a save that gets
    # slower silently erodes the preemption-tolerance contract (longer
    # torn-write windows, later final saves), and restore latency IS
    # the recovery-time floor after any crash
    ("ckpt.save_ms", False),
    ("ckpt.restore_ms", False),
    # auto-tuner v2 (ISSUE 10, bench "tune" block): search wall time
    # must not creep (the cost-model prune is the whole point), and
    # the winner's predicted/measured ratio is gated in BOTH
    # directions — two rows on one key make a two-sided drift gate
    # with the existing directional machinery (the absolute value is
    # CPU-relative on the CPU rig; cross-round DRIFT is the signal: a
    # drifting ratio means the cost model and the measured world are
    # coming apart)
    ("tune.search_seconds", False),
    ("tune.predicted_over_measured", False),
    ("tune.predicted_over_measured", True),
    # plan observatory (ISSUE 13, bench "profile" block): attribution
    # coverage dropping means the parser stopped explaining the
    # measured device step wall (a taxonomy/track regression, or a
    # runtime that moved its op events); the wire-term calibration
    # ratio is gated in BOTH directions — same two-row two-sided
    # pattern as tune.predicted_over_measured: the absolute value is
    # CPU-relative on the CPU rig, DRIFT means the cost model and the
    # measured world are coming apart
    ("profile.attribution_coverage", True),
    ("profile.calibration.wire_predicted_over_measured", False),
    ("profile.calibration.wire_predicted_over_measured", True),
    # pallas LSTM backward (ISSUE 14, bench "lstm" block): the
    # fwd+bwd op step must not quietly slow down, and the
    # pallas-over-recompute ratio is gated in BOTH directions — the
    # two-row two-sided drift pattern (the absolute is CPU-relative
    # on the CPU rig, where it prices the interpreter emulation, not
    # the kernel's HBM economics; a drifting ratio means one of the
    # two backward paths moved)
    ("lstm.op_ms.pallas_bwd", False),
    ("lstm.pallas_over_recompute", False),
    ("lstm.pallas_over_recompute", True),
    # the shipped-default backward's win over the recompute baseline
    # (kernel on TPU, residual-scan off-TPU) — a ratio creeping back
    # toward 1 means the residual backward is losing its edge
    ("lstm.auto_over_recompute", False),
    # paged-attention decode (ISSUE 16, bench "attn" block): the
    # kernel's decode-step time must not quietly slow down, and the
    # kernel-over-einsum ratio is gated in BOTH directions — the
    # two-row two-sided drift pattern (the absolute is CPU-relative
    # on the CPU rig, where it prices the interpreter emulation, not
    # the live-pages-only HBM economics; a drifting ratio means one
    # of the two executors moved)
    ("attn.step_ms.kernel", False),
    ("attn.kernel_over_einsum", False),
    ("attn.kernel_over_einsum", True),
    # numerics observatory (ISSUE 17, bench "numerics" block from
    # tools/numerics_report.py): each drift sentinel's accuracy
    # (1/(1+rel_err), ~1.0 when candidate and reference executors
    # agree) is gated in BOTH directions — the two-row two-sided
    # drift pattern: a FALLING accuracy means a kernel started
    # disagreeing with its reference (the regression the sentinels
    # exist to catch), a rising one means the reference moved; and
    # the host-side per-sample consume cost must not creep (it is
    # priced into the <=2% obs budget by check_obs_overhead)
    ("numerics.drift.lstm_bwd.accuracy", False),
    ("numerics.drift.lstm_bwd.accuracy", True),
    ("numerics.drift.paged_attn.accuracy", False),
    ("numerics.drift.paged_attn.accuracy", True),
    ("numerics.consume_us", False),
    # pipeline third axis (ISSUE 18, bench "tune.pp_trial" sub-block):
    # a pp>1 trial row's predicted-over-measured, gated in BOTH
    # directions — the same two-row two-sided drift pattern as the 2-D
    # tune gate above (the absolute is CPU-relative on the CPU rig; a
    # drifting ratio means the bubble + inter-stage-transfer pricing
    # and the measured 1F1B/GPipe schedule are coming apart)
    ("tune.pp_trial.predicted_over_measured", False),
    ("tune.pp_trial.predicted_over_measured", True),
    # disaggregated serving (ISSUE 19, bench "serve.disagg" block):
    # the disaggregated arm's client-observed TTFT tail and its
    # throughput over the mixed-regime stream — the two SLOs the
    # prefill/decode split exists to protect. Absolutes are
    # CPU-relative (the 'wire' is a host memcpy on the CPU rig);
    # cross-round drift is the signal: a creeping ttft_ms_p99 means
    # the prefill/transfer path got slower, a falling tokens_per_sec
    # means the decode pool did
    ("serve.disagg.ttft_ms_p99", False),
    ("serve.disagg.tokens_per_sec", True),
    # ops observatory (ISSUE 20, bench "ops" block from
    # tools/check_goodput.py): the clean-run goodput fraction must not
    # quietly fall (the instrumented loop losing wall to badput —
    # CPU-relative absolute, cross-round drift is the signal), and a
    # full alert-rule pass over the builtin set must not creep (it is
    # priced into the <=2% obs budget by check_obs_overhead)
    ("ops.goodput_fraction", True),
    ("ops.alert_eval_us", False),
)


def _get(doc, dotted):
    """Resolve ``a.b.-1.c`` through dicts and lists; None when any hop
    is missing or mistyped."""
    cur = doc
    for part in dotted.split("."):
        if isinstance(cur, dict):
            cur = cur.get(part)
        elif isinstance(cur, (list, tuple)):
            try:
                cur = cur[int(part)]
            except (ValueError, IndexError):
                return None
        else:
            return None
    return cur


def compare_secondary(current: dict, previous: dict,
                      max_drop: float = DEFAULT_MAX_DROP,
                      gates=SECONDARY_GATES) -> list:
    """Gate the serve/decode sub-blocks between two HARNESS-COMPATIBLE
    rounds (the caller has already established primary comparability).
    Returns one verdict per gate: ``ok`` / ``regression`` /
    ``explained`` (global ``regression_note``) / ``skipped`` (value
    absent on either side)."""
    note = current.get("regression_note")
    out = []
    for path, higher_better in gates:
        cur_v, prev_v = _get(current, path), _get(previous, path)
        row = {"gate": path, "higher_is_better": higher_better,
               "current": cur_v, "previous": prev_v}
        if not isinstance(cur_v, (int, float)) \
                or not isinstance(prev_v, (int, float)) \
                or prev_v <= 0 or cur_v <= 0:
            row["status"] = "skipped"
            row["why"] = "value missing or non-positive on one side"
            out.append(row)
            continue
        ratio = cur_v / prev_v
        row["ratio"] = round(ratio, 4)
        worse = (ratio < 1.0 - max_drop) if higher_better \
            else (ratio > 1.0 / (1.0 - max_drop))
        if worse:
            if note:
                row["status"] = "explained"
                row["why"] = f"regression_note: {note}"
            else:
                row["status"] = "regression"
                row["why"] = (f"moved {round((ratio - 1) * 100, 2)}% "
                              f"in the bad direction (> "
                              f"{max_drop * 100:.0f}%) with no "
                              f"explanation in-artifact")
        else:
            row["status"] = "ok"
        out.append(row)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("current", nargs="?", default=None,
                    help="current round artifact (default: latest "
                         "BENCH_r*.json in the repo root)")
    ap.add_argument("previous", nargs="?", default=None,
                    help="previous round artifact (default: "
                         "second-latest)")
    ap.add_argument("--max-drop", type=float, default=DEFAULT_MAX_DROP,
                    help="fail on an unexplained drop beyond this "
                         "fraction (default 0.15)")
    ap.add_argument("--max-rise", type=float, default=DEFAULT_MAX_RISE,
                    help="flag an unexplained rise beyond this "
                         "fraction (default 0.50)")
    args = ap.parse_args(argv)

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cur_path, prev_path = args.current, args.previous
    if cur_path is None:
        cur_path, _ = discover_rounds(root)
    if prev_path is None and cur_path is not None:
        # documented default: the latest round that is not the current
        # artifact itself (works when `current` was given explicitly)
        from bench_artifacts import round_paths
        others = [p for p in round_paths(root)
                  if os.path.abspath(p) != os.path.abspath(cur_path)]
        prev_path = others[-1] if others else None
    if cur_path is None:
        print(json.dumps({"status": "no_data",
                          "why": "no BENCH_r*.json artifacts found"}))
        return 2
    cur = load_bench(cur_path)
    prev = load_bench(prev_path) if prev_path else None
    result = compare(cur, prev, max_drop=args.max_drop,
                     max_rise=args.max_rise)
    result["current_path"] = cur_path
    result["previous_path"] = prev_path
    # secondary serve/decode gates apply only between rounds the
    # primary comparison established as harness-compatible (same
    # bench_version; a version bump re-baselines the sub-blocks too)
    if (result["status"] in ("ok", "regression", "suspicious_rise",
                             "explained")
            and isinstance(cur, dict) and isinstance(prev, dict)
            and cur.get("bench_version") == prev.get("bench_version")):
        result["secondary"] = compare_secondary(
            cur, prev, max_drop=args.max_drop)
    print(json.dumps(result, indent=2))
    if result["status"] == "regression" or any(
            r["status"] == "regression"
            for r in result.get("secondary", [])):
        return 1
    if result["status"] == "no_data":
        # fail CLOSED on unreadable/missing artifacts: a gate that
        # measured nothing must not show green
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
