"""Synthetic load generator for the serving subsystem.

Drives a :class:`~parallax_tpu.serve.session.ServeSession` with
closed-loop clients (each thread submits, waits for the result, then
submits again — the standard saturating-load shape) over a caller-
supplied feed generator, and reports per-request outcomes alongside
the session's own ``serve.*`` metrics. Used by
``tools/check_serve_slo.py`` (the tier-1 SLO contract), the BENCH
"serve" section (bench.py), and runnable directly::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/loadgen.py

which serves a small MLP scorer under a mixed-length load and prints
one JSON report.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_load(session, make_feed, n_requests: int, concurrency: int = 4,
             deadline_ms=None, max_new_tokens=None,
             result_timeout_s: float = 120.0) -> dict:
    """Submit ``n_requests`` through ``concurrency`` closed-loop client
    threads; ``make_feed(i)`` builds request ``i``'s feed. Returns the
    outcome/latency report (shed and timed-out requests are counted,
    not errors)."""
    import numpy as np

    from parallax_tpu.serve import (DeadlineExceeded, ServeClosed,
                                    ServeOverloaded)

    lock = threading.Lock()
    counter = {"next": 0}
    outcomes = {"completed": 0, "shed": 0, "timeout": 0, "failed": 0}
    latencies = []
    errors = []

    def client():
        while True:
            with lock:
                i = counter["next"]
                if i >= n_requests:
                    return
                counter["next"] = i + 1
            try:
                req = session.submit(make_feed(i),
                                     deadline_ms=deadline_ms,
                                     max_new_tokens=max_new_tokens)
            except ServeOverloaded:
                with lock:
                    outcomes["shed"] += 1
                continue
            try:
                req.result(timeout=result_timeout_s)
                with lock:
                    outcomes["completed"] += 1
                    latencies.append(req.latency_s())
            except DeadlineExceeded:
                with lock:
                    outcomes["timeout"] += 1
            except (ServeClosed, TimeoutError) as e:
                with lock:
                    outcomes["failed"] += 1
                    errors.append(f"{type(e).__name__}: {e}")

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, name=f"loadgen-{k}",
                                daemon=True)
               for k in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    lat_ms = sorted(v * 1e3 for v in latencies)

    def pct(q):
        if not lat_ms:
            return None
        import math
        return round(lat_ms[min(len(lat_ms) - 1,
                                math.ceil(q * len(lat_ms)) - 1)], 3)

    return {
        "submitted": n_requests,
        "completed": outcomes["completed"],
        "shed": outcomes["shed"],
        "timeouts": outcomes["timeout"],
        "failed": outcomes["failed"],
        "errors": errors[:5],
        "wall_s": round(wall, 3),
        "qps": round(outcomes["completed"] / wall, 2) if wall > 0 else None,
        "latency_ms": {"p50": pct(0.50), "p95": pct(0.95),
                       "max": round(lat_ms[-1], 3) if lat_ms else None},
        "deadline_ms": deadline_ms,
        "concurrency": concurrency,
    }


def demo_session(max_batch: int = 8, length_buckets=(16, 32),
                 dim: int = 384, layers: int = 4, max_queue: int = 128,
                 max_wait_ms: float = 2.0, default_deadline_ms=None):
    """A small-MLP one-shot scorer behind a ServeSession — the shared
    rig of the CLI, the SLO tool and the bench serve section. Returns
    ``(session, make_feed)``."""
    import jax
    import numpy as np

    import parallax_tpu as parallax

    rng = jax.random.PRNGKey(0)
    ws = []
    for i in range(layers):
        rng, k = jax.random.split(rng)
        ws.append(jax.random.normal(k, (dim, dim)) / np.sqrt(dim))
    params = {"w": ws}

    def infer_fn(params, batch):
        x = batch["x"]                       # [B, L, dim]
        for w in params["w"]:
            x = jax.nn.tanh(x @ w)
        return {"score": x.mean(axis=(1, 2))}

    cfg = parallax.Config(serve_config=parallax.ServeConfig(
        max_batch=max_batch, max_wait_ms=max_wait_ms,
        max_queue=max_queue, length_buckets=list(length_buckets),
        default_deadline_ms=default_deadline_ms))
    sess = parallax.ServeSession(
        infer_fn, params,
        example_feed={"x": np.zeros((length_buckets[-1], dim),
                                    np.float32)},
        config=cfg, ragged_feeds=("x",))

    lo, hi = max(1, length_buckets[0] // 2), length_buckets[-1]

    def make_feed(i):
        # per-request generator: make_feed is called concurrently from
        # every client thread, and numpy Generators are not
        # thread-safe — a shared one would corrupt the mixed-length
        # coverage this rig exists to produce
        r = np.random.default_rng(1000 + i)
        L = int(r.integers(lo, hi + 1))
        return {"x": r.standard_normal((L, dim)).astype(np.float32)}

    return sess, make_feed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--deadline-ms", type=float, default=None)
    args = ap.parse_args(argv)
    sess, make_feed = demo_session()
    try:
        report = run_load(sess, make_feed, args.requests,
                          concurrency=args.concurrency,
                          deadline_ms=args.deadline_ms)
        report["serve_metrics"] = sess.stats()
    finally:
        sess.close()
    print(json.dumps(report, indent=2, default=str))
    return 0 if report["failed"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
