"""Synthetic load generator for the serving subsystem.

Drives a :class:`~parallax_tpu.serve.session.ServeSession` with
closed-loop clients (each thread submits, waits for the result, then
submits again — the standard saturating-load shape) over a caller-
supplied feed generator, and reports per-request outcomes (latency,
time-to-first-token, emitted tokens) alongside the session's own
``serve.*`` metrics. Used by ``tools/check_serve_slo.py`` (the tier-1
SLO contract), the BENCH "serve" section (bench.py), and runnable
directly::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/loadgen.py

which serves a small MLP scorer under a mixed-length load and prints
one JSON report.

**Concurrency sweep** (ISSUE 6): ``--mode decode --sweep 8,16,32,64``
brings up one continuous-decode session per offered concurrency level
(paged KV + chunked prefill + speculative decoding by default) and
stamps tokens/sec and TTFT per level — the 8x-64x-concurrency claim as
one artifact, not prose. ``sweep_decode()`` is the API bench.py stamps
into the ``serve.continuous`` block.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _pct(sorted_ms, q):
    # one quantile rule repo-wide (obs/metrics.nearest_rank)
    from parallax_tpu.obs.metrics import nearest_rank
    v = nearest_rank(sorted_ms, q)
    return round(v, 3) if v is not None else None


def run_load(session, make_feed, n_requests: int, concurrency: int = 4,
             deadline_ms=None, max_new_tokens=None,
             result_timeout_s: float = 120.0,
             submit_kw=None) -> dict:
    """Submit ``n_requests`` through ``concurrency`` closed-loop client
    threads; ``make_feed(i)`` builds request ``i``'s feed. Returns the
    outcome/latency report (shed and timed-out requests are counted,
    not errors). ``submit_kw`` (e.g. ``{"tenant": "a"}``) is forwarded
    to every ``session.submit``. ``max_new_tokens`` may be a CALLABLE
    ``i -> int`` (per-request decode budgets — the mixed-regime rig's
    short-decode/long-decode split rides this)."""
    from parallax_tpu.serve import (DeadlineExceeded, ServeClosed,
                                    ServeOverloaded)

    submit_kw = submit_kw or {}

    lock = threading.Lock()
    counter = {"next": 0}
    outcomes = {"completed": 0, "shed": 0, "timeout": 0, "failed": 0}
    latencies = []
    ttfts = []
    tokens = [0]
    errors = []

    def client():
        while True:
            with lock:
                i = counter["next"]
                if i >= n_requests:
                    return
                counter["next"] = i + 1
            mnt = (max_new_tokens(i) if callable(max_new_tokens)
                   else max_new_tokens)
            try:
                req = session.submit(make_feed(i),
                                     deadline_ms=deadline_ms,
                                     max_new_tokens=mnt,
                                     **submit_kw)
            except ServeOverloaded:
                with lock:
                    outcomes["shed"] += 1
                continue
            try:
                res = req.result(timeout=result_timeout_s)
                n_tok = len(res) if hasattr(res, "__len__") else 0
                t_first = req.t_first_token or req.t_done
                with lock:
                    outcomes["completed"] += 1
                    latencies.append(req.latency_s())
                    tokens[0] += n_tok
                    if t_first is not None:
                        ttfts.append(t_first - req.t_enqueue)
            except DeadlineExceeded:
                with lock:
                    outcomes["timeout"] += 1
            except (ServeClosed, TimeoutError) as e:
                with lock:
                    outcomes["failed"] += 1
                    errors.append(f"{type(e).__name__}: {e}")

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, name=f"loadgen-{k}",
                                daemon=True)
               for k in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    lat_ms = sorted(v * 1e3 for v in latencies)
    ttft_ms = sorted(v * 1e3 for v in ttfts)
    return {
        "submitted": n_requests,
        "completed": outcomes["completed"],
        "shed": outcomes["shed"],
        "timeouts": outcomes["timeout"],
        "failed": outcomes["failed"],
        "errors": errors[:5],
        "wall_s": round(wall, 3),
        "qps": round(outcomes["completed"] / wall, 2) if wall > 0 else None,
        "latency_ms": {"p50": _pct(lat_ms, 0.50), "p95": _pct(lat_ms, 0.95),
                       "p99": _pct(lat_ms, 0.99),
                       "max": round(lat_ms[-1], 3) if lat_ms else None},
        # time-to-first-token, measured CLIENT-side per request (equals
        # full latency in one-shot mode, where the only token is the
        # result)
        "ttft_ms": {"p50": _pct(ttft_ms, 0.50), "p95": _pct(ttft_ms, 0.95),
                    "p99": _pct(ttft_ms, 0.99),
                    "max": round(ttft_ms[-1], 3) if ttft_ms else None},
        "tokens": tokens[0],
        "tokens_per_sec": (round(tokens[0] / wall, 2)
                           if wall > 0 and tokens[0] else None),
        "deadline_ms": deadline_ms,
        "concurrency": concurrency,
    }


def demo_session(max_batch: int = 8, length_buckets=(16, 32),
                 dim: int = 384, layers: int = 4, max_queue: int = 128,
                 max_wait_ms: float = 2.0, default_deadline_ms=None):
    """A small-MLP one-shot scorer behind a ServeSession — the shared
    rig of the CLI, the SLO tool and the bench serve section. Returns
    ``(session, make_feed)``."""
    import jax
    import numpy as np

    import parallax_tpu as parallax

    rng = jax.random.PRNGKey(0)
    ws = []
    for i in range(layers):
        rng, k = jax.random.split(rng)
        ws.append(jax.random.normal(k, (dim, dim)) / np.sqrt(dim))
    params = {"w": ws}

    def infer_fn(params, batch):
        x = batch["x"]                       # [B, L, dim]
        for w in params["w"]:
            x = jax.nn.tanh(x @ w)
        return {"score": x.mean(axis=(1, 2))}

    cfg = parallax.Config(serve_config=parallax.ServeConfig(
        max_batch=max_batch, max_wait_ms=max_wait_ms,
        max_queue=max_queue, length_buckets=list(length_buckets),
        default_deadline_ms=default_deadline_ms))
    sess = parallax.ServeSession(
        infer_fn, params,
        example_feed={"x": np.zeros((length_buckets[-1], dim),
                                    np.float32)},
        config=cfg, ragged_feeds=("x",))

    lo, hi = max(1, length_buckets[0] // 2), length_buckets[-1]

    def make_feed(i):
        # per-request generator: make_feed is called concurrently from
        # every client thread, and numpy Generators are not
        # thread-safe — a shared one would corrupt the mixed-length
        # coverage this rig exists to produce
        r = np.random.default_rng(1000 + i)
        L = int(r.integers(lo, hi + 1))
        return {"x": r.standard_normal((L, dim)).astype(np.float32)}

    return sess, make_feed


def shared_prefix_feed(Ts: int = 8, vocab: int = 256,
                       prefix_share: float = 0.5, pool_size: int = 4,
                       pool_seed: int = 777):
    """A ``make_feed(i)`` with a DETERMINISTIC shared-prefix pool
    (ISSUE 15): a ``prefix_share`` fraction of requests draw their
    source from ``pool_size`` fixed sequences (the system-prompt /
    template / retry population) and the rest are unique. Which
    requests are shared — and which pool member they draw — is a pure
    function of ``i``, so an A/B rig (sharing on vs off) and a
    bit-identity sweep replay the EXACT same request stream."""
    import numpy as np

    if not 0.0 <= float(prefix_share) <= 1.0:
        raise ValueError(
            f"prefix_share must be in [0, 1], got {prefix_share}")
    pr = np.random.default_rng(pool_seed)
    pool = [pr.integers(3, vocab, (Ts,)).astype(np.int32)
            for _ in range(max(1, int(pool_size)))]

    def make_feed(i):
        r = np.random.default_rng(3000 + i)
        if r.random() < prefix_share:
            return {"src": pool[int(r.integers(0, len(pool)))]}
        L = int(r.integers(max(2, Ts // 2), Ts + 1))
        return {"src": r.integers(3, vocab, (L,)).astype(np.int32)}

    return make_feed


def mixed_regime_feed(Ts: int = 8, vocab: int = 256,
                      long_prefill_share: float = 0.5,
                      short_decode: int = 2, long_decode: int = 8,
                      key: str = "src", seed: int = 4000):
    """The disaggregation traffic shape (ISSUE 19): a deterministic
    mix of the two regimes that pull a colocated replica in opposite
    directions — LONG-prefill/SHORT-decode requests (full-length
    source, ``short_decode`` new tokens: the prefill-bound half) and
    SHORT-prefill/LONG-decode requests (minimal source,
    ``long_decode`` new tokens: the decode-bound half). Which regime
    request ``i`` belongs to is a pure function of ``i``, so the
    colocated and disaggregated arms of an A/B replay the EXACT same
    request stream. Returns ``(make_feed, max_new_tokens)``; the
    second is the ``i -> int`` callable ``run_load`` resolves per
    request."""
    import numpy as np

    if not 0.0 <= float(long_prefill_share) <= 1.0:
        raise ValueError(f"long_prefill_share must be in [0, 1], "
                         f"got {long_prefill_share}")

    def _regime(r):
        # first draw from the per-i generator decides the regime, so
        # make_feed and max_new_tokens agree without shared state
        return r.random() < long_prefill_share

    def make_feed(i):
        r = np.random.default_rng(seed + i)
        L = Ts if _regime(r) else max(2, Ts // 4)
        return {key: r.integers(3, vocab, (L,)).astype(np.int32)}

    def max_new_tokens(i):
        r = np.random.default_rng(seed + i)
        return short_decode if _regime(r) else long_decode

    return make_feed, max_new_tokens


def demo_disagg_rig(slots: int = 4, T: int = 12, Ts: int = 8,
                    model_dim: int = 32, num_layers: int = 2,
                    vocab: int = 64, page_size: int = 4,
                    max_queue: int = 4096):
    """The disaggregation A/B fixture (bench ``serve.disagg`` block
    and tests): a paged f32 tiny-NMT decode program plus a replica
    factory with the prefix cache ON (the import surface). Every
    replica shares ONE program instance, so the colocated arm, the
    prefill pool and the decode pool all ride the same jit caches.
    Build the colocated arm as ``ServeFleet(make_replica, ...)`` and
    the disaggregated arm as ``DisaggFleet(make_replica,
    make_replica, ...)`` over the same :func:`mixed_regime_feed`
    stream (feed key ``"src"``). Returns ``make_replica``."""
    import jax
    import jax.numpy as jnp

    import parallax_tpu as parallax
    from parallax_tpu.models import nmt
    from parallax_tpu.serve import NMTDecodeProgram, ServeSession

    cfg = nmt.tiny_config(vocab_size=vocab, model_dim=model_dim,
                          num_heads=4, mlp_dim=2 * model_dim,
                          num_layers=num_layers, max_len=max(T, Ts),
                          num_partitions=1,
                          compute_dtype=jnp.float32)
    params = nmt.build_model(cfg).init_fn(jax.random.PRNGKey(0))
    prog = NMTDecodeProgram(cfg, max_src_len=Ts, max_len=T,
                            page_size=page_size,
                            pool_pages=slots * (T // page_size))
    pcfg = parallax.Config(serve_config=parallax.ServeConfig(
        max_batch=slots, max_queue=max_queue, prefix_cache=True))

    def make_replica(rid, **serve_kw):
        return ServeSession(program=prog, params=params, config=pcfg,
                            **serve_kw)

    return make_replica


def demo_decode_session(slots: int = 16, T: int = 16, Ts: int = 8,
                        page_size: int = 4, pool_pages=None,
                        prefill_chunk_layers=1, spec_tokens: int = 2,
                        model_dim: int = 64, num_layers: int = 2,
                        vocab: int = 256, max_queue: int = 4096,
                        paged: bool = True, speculative: bool = True,
                        prefix_cache: bool = False,
                        prefix_cache_max_pages=None,
                        tenant_quotas=None, slo_classes=None,
                        metrics=None, attn_impl=None,
                        compute_dtype=None):
    """A tiny-NMT continuous-decode session with the full ISSUE 6
    stack on by default — paged KV pool, chunked prefill, layer-skip
    speculative draft — plus the ISSUE 15 knobs (prefix cache, tenant
    quotas, SLO classes) off by default. Returns ``(session,
    make_feed)``; ``make_feed`` produces mixed-length sources.
    ``paged=False`` / ``speculative=False`` select the dense / plain
    ablations (the A/B rigs of tools/nmt_decode_timing.py and the
    sweep)."""
    import jax
    import numpy as np

    import parallax_tpu as parallax
    from parallax_tpu.models import nmt
    from parallax_tpu.serve import NMTDecodeProgram

    cfg_kw = dict(vocab_size=vocab, model_dim=model_dim,
                  num_heads=4, mlp_dim=2 * model_dim,
                  num_layers=num_layers, max_len=max(T, Ts),
                  num_partitions=1)
    if compute_dtype is not None:
        # executor A/B rigs pin float32: the kernel/einsum token-
        # identity contract is exact there (bf16 differs within
        # rounding noise — see ops/pallas_paged_attention)
        cfg_kw.update(compute_dtype=compute_dtype)
    cfg = nmt.tiny_config(**cfg_kw)
    params = nmt.build_model(cfg).init_fn(jax.random.PRNGKey(0))
    kw = {}
    if paged:
        if pool_pages is None:
            pool_pages = slots * (T // page_size)
        kw.update(page_size=page_size, pool_pages=pool_pages)
    if attn_impl is not None:
        # paged-attention executor A/B ('kernel' | 'einsum' | 'auto');
        # see ops/pallas_paged_attention and tools/check_paged_attn_serve
        kw.update(attn_impl=attn_impl)
    if prefill_chunk_layers:
        kw.update(prefill_chunk_layers=prefill_chunk_layers)
    if speculative and spec_tokens:
        from parallax_tpu.serve.adapters import layer_skip_draft
        dcfg, dparams = layer_skip_draft(cfg, params)
        kw.update(spec_tokens=spec_tokens, draft_cfg=dcfg,
                  draft_params=dparams)
    prog = NMTDecodeProgram(cfg, max_src_len=Ts, max_len=T, **kw)
    pcfg = parallax.Config(serve_config=parallax.ServeConfig(
        max_batch=slots, max_queue=max_queue,
        prefix_cache=prefix_cache,
        prefix_cache_max_pages=prefix_cache_max_pages,
        tenant_quotas=tenant_quotas, slo_classes=slo_classes))
    sess = parallax.ServeSession(program=prog, params=params,
                                 config=pcfg, metrics=metrics)

    def make_feed(i):
        r = np.random.default_rng(2000 + i)
        L = int(r.integers(max(2, Ts // 2), Ts + 1))
        return {"src": r.integers(3, vocab, (L,)).astype(np.int32)}

    return sess, make_feed


def demo_decode_fleet(replicas: int = 2, slots: int = 4, T: int = 12,
                      Ts: int = 8, model_dim: int = 32,
                      num_layers: int = 2, vocab: int = 64,
                      page_size: int = 4, paged: bool = True,
                      max_queue: int = 4096, submesh: bool = True,
                      fleet_config=None, faults=None, flight=None,
                      anomaly=None, metrics=None):
    """A replicated tiny-NMT continuous-decode :class:`ServeFleet` —
    the chaos-harness rig (tools/check_fleet_faults.py) and the bench
    ``serve.fleet`` block.

    Every replica is a full ServeSession (own scheduler thread, own
    queue) on its own submesh when the device count splits
    (``submesh=True``), else on one shared mesh. All replicas share
    ONE program instance and one host param pytree, so replica
    spin-up rides the jit caches — the first replica compiles, the
    rest come up compile-free (the PR 3 cache story at fleet scale).
    Greedy decode is deterministic, so every replica emits
    bit-identical tokens for the same request — the property failover
    retry leans on. Returns ``(fleet, make_feed, params, cfg)``;
    ``make_feed(i)`` is deterministic per ``i`` so an unfaulted
    baseline can replay the exact request set."""
    import jax
    import numpy as np

    import parallax_tpu as parallax
    from parallax_tpu.core import mesh as mesh_lib
    from parallax_tpu.models import nmt
    from parallax_tpu.serve import (FleetConfig, NMTDecodeProgram,
                                    ServeFleet, ServeSession)

    import jax.numpy as jnp
    # f32 compute: the bit-identity bar (failover retries vs standalone
    # greedy) holds exactly in f32; bf16 rounding differences between
    # the batched cached step and the reference decode can flip argmax
    # at near-ties, which is a dtype artifact, not a fleet bug
    cfg = nmt.tiny_config(vocab_size=vocab, model_dim=model_dim,
                          num_heads=4, mlp_dim=2 * model_dim,
                          num_layers=num_layers, max_len=max(T, Ts),
                          num_partitions=1,
                          compute_dtype=jnp.float32)
    params = nmt.build_model(cfg).init_fn(jax.random.PRNGKey(0))
    kw = {}
    if paged:
        kw.update(page_size=page_size,
                  pool_pages=slots * (T // page_size))
    prog = NMTDecodeProgram(cfg, max_src_len=Ts, max_len=T, **kw)
    pcfg = parallax.Config(serve_config=parallax.ServeConfig(
        max_batch=slots, max_queue=max_queue))

    fc = fleet_config or FleetConfig(num_replicas=replicas)
    devs = jax.devices()
    # split ALL devices across the INITIAL replica count (with 8 CPU
    # devices and 2 replicas: two 4-device submeshes, no idle devices);
    # replicas churned/scaled past that wrap onto existing groups —
    # sharing a submesh also means sharing its compiled executables
    groups = max(1, int(fc.num_replicas))
    per = len(devs) // groups
    meshes = {}

    def make_replica(rid, **serve_kw):
        if submesh and per >= 1 and groups > 1:
            g = int(rid) % groups
            mesh = meshes.get(g)
            if mesh is None:
                mesh = meshes[g] = mesh_lib.build_mesh(
                    devices=devs[g * per:(g + 1) * per],
                    num_partitions=1)
        else:
            mesh = meshes.setdefault(
                "shared", mesh_lib.build_mesh(num_partitions=1))
        return ServeSession(program=prog, params=params, config=pcfg,
                            mesh=mesh, **serve_kw)

    fleet = ServeFleet(make_replica, config=fc, metrics=metrics,
                       flight=flight, anomaly=anomaly, faults=faults)

    def make_feed(i):
        r = np.random.default_rng(2000 + i)
        L = int(r.integers(max(2, Ts // 2), Ts + 1))
        return {"src": r.integers(3, vocab, (L,)).astype(np.int32)}

    return fleet, make_feed, params, cfg


def sweep_decode(levels=(8, 16, 32, 64), requests_per_level=None,
                 result_timeout_s: float = 300.0, **session_kw) -> list:
    """The concurrency sweep: one fresh continuous-decode session per
    offered level (slots == offered closed-loop clients), tokens/sec
    and TTFT stamped per level. Sessions are rebuilt per level so
    every row starts from a cold queue and clean metrics; warmup
    compiles happen at construction, OUTSIDE the measured window."""
    from tools import serve_report

    rows = []
    for level in levels:
        n_req = requests_per_level or max(2 * level, 16)
        sess, make_feed = demo_decode_session(slots=level, **session_kw)
        try:
            rep = run_load(sess, make_feed, n_req, concurrency=level,
                           result_timeout_s=result_timeout_s)
            stats = sess.stats()
            records = sess.request_records()
        finally:
            sess.close()
        # trace-derived attribution (ISSUE 12): per-phase TTFT shares
        # and the per-percentile dominant-cause report for this level
        attribution = serve_report.analyze(records)
        rows.append({
            "offered_concurrency": level,
            "slots": level,
            "requests": n_req,
            "completed": rep["completed"],
            "failed": rep["failed"],
            "tokens": rep["tokens"],
            "tokens_per_sec": rep["tokens_per_sec"],
            "ttft_ms": rep["ttft_ms"],
            "latency_ms": rep["latency_ms"],
            "qps": rep["qps"],
            "recompiles": stats.get("serve.recompiles", 0),
            "kv_pages_in_use_after": stats.get("serve.kv_pages_in_use"),
            "kv_refill_deferred": stats.get("serve.kv_refill_deferred",
                                            0),
            "spec_accept_rate": stats.get("serve.spec_accept_rate"),
            "decode_steps": stats.get("serve.decode_steps"),
            "ttft_decomp": serve_report.ttft_shares(records),
            "deadline_miss_budget_consumed":
                serve_report.deadline_miss_budget_consumed(records),
            "attribution": attribution,
        })
        print(f"# sweep level {level}: {rep['tokens_per_sec']} tok/s, "
              f"ttft p50 {rep['ttft_ms']['p50']}ms", flush=True)
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--mode", choices=("oneshot", "decode"),
                    default="oneshot")
    ap.add_argument("--sweep", type=str, default=None,
                    help="comma-separated offered-concurrency levels; "
                         "decode mode only (e.g. 8,16,32,64)")
    ap.add_argument("--prefix-share", type=float, default=None,
                    help="decode mode: fraction of requests drawing "
                         "their source from a deterministic shared "
                         "pool (e.g. 0.5); enables the prefix cache")
    ap.add_argument("--prefix-pool", type=int, default=4,
                    help="size of the shared-prefix pool")
    ap.add_argument("--mixed-regime", action="store_true",
                    help="decode mode: the disaggregation traffic "
                         "shape — a deterministic long-prefill/"
                         "short-decode vs short-prefill/long-decode "
                         "mix with per-request decode budgets")
    args = ap.parse_args(argv)
    if args.sweep:
        if args.prefix_share is not None:
            ap.error("--prefix-share is not wired into --sweep; the "
                     "sweep prices raw concurrency (run --mode decode "
                     "--prefix-share for the shared-prefix rig, or "
                     "tools/check_prefix_reuse.py for the full A/B)")
        levels = tuple(int(x) for x in args.sweep.split(","))
        rows = sweep_decode(levels=levels)
        print(json.dumps({"sweep": rows}, indent=2, default=str))
        return 0 if all(r["failed"] == 0 for r in rows) else 1
    if args.mixed_regime and args.prefix_share is not None:
        ap.error("--mixed-regime and --prefix-share are separate "
                 "traffic shapes; pick one")
    mnt = None
    if args.mode == "decode":
        sess, make_feed = demo_decode_session(
            prefix_cache=args.prefix_share is not None)
        if args.prefix_share is not None:
            make_feed = shared_prefix_feed(
                prefix_share=args.prefix_share,
                pool_size=args.prefix_pool)
        if args.mixed_regime:
            make_feed, mnt = mixed_regime_feed()
    else:
        if args.prefix_share is not None:
            ap.error("--prefix-share needs --mode decode (the prefix "
                     "cache lives on the continuous-decode path)")
        if args.mixed_regime:
            ap.error("--mixed-regime needs --mode decode (decode "
                     "budgets only exist on the continuous-decode "
                     "path)")
        sess, make_feed = demo_session()
    try:
        report = run_load(sess, make_feed, args.requests,
                          concurrency=args.concurrency,
                          deadline_ms=args.deadline_ms,
                          max_new_tokens=mnt)
        report["serve_metrics"] = sess.stats()
    finally:
        sess.close()
    print(json.dumps(report, indent=2, default=str))
    return 0 if report["failed"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
