"""Flagship wire-bytes accounting: sparse path vs dense all-reduce.

The BASELINE.json north-star secondary metric is "sparse-grad bytes on
wire" — the reference's PS win is shipping only the touched (ids, rows)
of the 793k-vocab embedding/softmax tables instead of dense [V, D]
gradients (reference: graph_transform_lib.py:1041-1211). The accounting
is trace-time (ops/embedding.py records per-lookup wire terms while the
step traces), so the REAL flagship config can be measured anywhere: this
script abstractly evaluates the full hybrid training step (no parameter
allocation, no execution) on an 8-virtual-device CPU mesh and prints the
accounting as one JSON line.

Run: python tools/wire_bytes_report.py [--out WIRE_BYTES.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count"
                                 "=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def flagship_accounting(n_chips: int = 8, batch_per_chip: int = 128,
                        num_steps: int = 20, table_dtype: str = "float32",
                        dedup_capacity=None):
    """Build the bench's flagship engine (793,470-vocab LM1B, HYBRID,
    slices mode) and return its wire-bytes accounting from an abstract
    trace of one training step.

    ``table_dtype='bfloat16'`` halves every row plane on the wire (the
    accounting models the element size exactly — ops/embedding.py);
    ``dedup_capacity`` declares the guarded per-device unique-id slot
    count (PSConfig.dedup_capacity) — the report then also verifies the
    declared capacity against the REAL distinct-id counts of the seeded
    batch so the committed number is never the optimistic lower bound of
    an overflowing configuration."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from parallax_tpu.common.config import (CommunicationConfig,
                                            ParallaxConfig, PSConfig)
    from parallax_tpu.core import engine as engine_lib, mesh as mesh_lib
    from parallax_tpu.models import lm1b

    devices = jax.devices()[:n_chips]
    mesh = mesh_lib.build_mesh(devices, num_partitions=n_chips)
    cfg = lm1b.LM1BConfig(num_partitions=n_chips,
                          sparse_grad_mode="slices",
                          table_dtype=jnp.dtype(table_dtype))
    model = lm1b.build_model(cfg)
    batch = lm1b.make_batch(np.random.default_rng(0),
                            batch_per_chip * n_chips, num_steps,
                            cfg.vocab_size)
    overflow_free = None

    def max_distinct(arr):
        return max(len(np.unique(c))
                   for c in np.split(arr.reshape(-1), n_chips))

    if dedup_capacity == "auto":
        # Per-table capacities from the REAL distinct-id profile of the
        # seeded batch (+ two 128-blocks of margin), per lookup: the emb
        # table gathers input ids (Zipf, heavy duplication); the softmax
        # tables gather labels + a 1/n_chips slice of the log-uniform
        # candidates (distinct count upper-bounded by labels-distinct +
        # slice length). The runtime lax.cond guard keeps any
        # out-of-profile step exact regardless.
        def padded(b):
            return (b // 128 + 2) * 128

        emb_cap = padded(max_distinct(batch["x"]))
        sm_cap = padded(max_distinct(batch["y"])
                        + cfg.num_samples // n_chips)
        # path keys: emb and softmax_w share a shape in the flagship
        dedup_capacity = {"emb": emb_cap, "softmax_w": sm_cap,
                          "softmax_b": sm_cap}
        overflow_free = True  # by construction, for the measured batch
    elif isinstance(dedup_capacity, dict):
        # round-trip of an 'auto'-style dict: check each declared table
        # against its own lookup's distinct-id bound
        emb_bound = max_distinct(batch["x"])
        sm_bound = (max_distinct(batch["y"])
                    + cfg.num_samples // n_chips)
        bounds = {"emb": emb_bound, "softmax_w": sm_bound,
                  "softmax_b": sm_bound}
        overflow_free = all(
            bounds.get(k, 0) <= v for k, v in dedup_capacity.items())
    elif dedup_capacity is not None:
        bound = max(max_distinct(batch["x"]),
                    max_distinct(batch["y"])
                    + cfg.num_samples // n_chips)
        overflow_free = bool(bound <= dedup_capacity)
    config = ParallaxConfig(
        run_option="HYBRID", search_partitions=False,
        sparse_grad_mode="slices",
        communication_config=CommunicationConfig(
            ps_config=PSConfig(dedup_capacity=dedup_capacity)))
    eng = engine_lib.Engine(model, mesh, config, batch)

    # Abstract evaluation: traces the step (filling the per-lookup wire
    # records) without allocating the 793k-vocab tables or running math.
    abstract_state = jax.eval_shape(eng._init_jit, 0)
    abstract_batch = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                      for k, v in eng.shard_batch(batch).items()}
    with eng.mesh:
        jax.eval_shape(eng._step_jit, abstract_state, abstract_batch)
    wire = eng.sparse_wire_bytes_per_step()
    # Derived ratios come from tune/costmodel.py — the ONE owner of the
    # wire-byte math (ISSUE 10; this script used to duplicate it).
    # The reference baseline: TF ships fp32 dense gradients whatever
    # the table dtype (BASELINE.md). The engine's dense alternative
    # counts the tables in their OWN dtype; all lm1b tables share
    # table_dtype, so the fp32 reference is a pure element-size
    # rescale of it.
    from parallax_tpu.tune import costmodel
    summary = costmodel.wire_summary(
        wire, table_elem_bytes=jnp.dtype(cfg.table_dtype).itemsize)
    return {
        "config": {
            "model": "lm1b", "vocab_size": cfg.vocab_size,
            "emb_dim": cfg.emb_dim, "proj_dim": cfg.proj_dim,
            "batch_size": batch_per_chip * n_chips,
            "num_steps": num_steps, "n_chips": n_chips,
            "run_option": "HYBRID", "sparse_grad_mode": "slices",
            "table_dtype": str(table_dtype),
            "dedup_capacity": dedup_capacity,
            "dedup_capacity_overflow_free": overflow_free,
        },
        **wire,
        "sparse_over_dense": summary["sparse_over_dense"],
        "dense_fp32_reference_bytes":
            summary["dense_fp32_reference_bytes"],
        "sparse_over_dense_fp32_ref":
            summary["sparse_over_dense_fp32_ref"],
    }


def pipeline_plan_section(pipeline: dict, num_devices: int = 8,
                          max_pp=None):
    """Per-plan inter-stage wire accounting for every pp > 1 plan the
    tuner can emit for a model with the given pipeline capability
    record (ISSUE 18 satellite). Pure math off the ONE wire owner
    (tune/costmodel.pipeline_wire_bytes / pipeline_bubble) — the same
    figures ``predict`` folds into ``wire_pp_s``, reported here as raw
    bytes so the report stays execution-free like the rest of the
    accounting."""
    from parallax_tpu.tune import costmodel
    from parallax_tpu.tune.search import emittable_plans

    act = float(pipeline.get("act_bytes") or 0.0)
    if not act:
        act = (float(pipeline.get("global_batch") or 0)
               * float(pipeline.get("model_dim") or 0)
               * float(pipeline.get("act_itemsize") or 4))
    schedule = str(pipeline.get("schedule") or "gpipe")
    rows = []
    for plan in emittable_plans(num_devices,
                                max_pp=max_pp or num_devices,
                                pipeline=pipeline):
        if plan.pp == 1:
            continue
        V = max(int(plan.virtual_stages), 1)
        M = int(plan.microbatches
                or pipeline.get("microbatches") or 1)
        w = costmodel.pipeline_wire_bytes(
            act, M, plan.pp, V, schedule=schedule,
            dp=plan.dp, tp=plan.tp)
        rows.append({
            "plan": plan.describe(),
            "pp": plan.pp,
            "schedule": schedule,
            "per_hop_bytes": w["per_hop_bytes"],
            "activation_bytes": w["activation_bytes"],
            "cotangent_bytes": w["cotangent_bytes"],
            "total_bytes": w["total_bytes"],
            "ticks": w["ticks"],
            "bubble_fraction": w["bubble_fraction"],
            "microbatches_scheduled": w["microbatches_scheduled"],
        })
    return {
        "act_bytes_per_boundary": act,
        "num_devices": num_devices,
        "plans": rows,
    }


def _demo_pipeline_record():
    """The pipeline capability record of the tiny pipeline LM the rest
    of the tooling (bench tune block, mesh_search_driver pp pool)
    exercises — so --pipeline reports the same plan pool they
    measure."""
    from parallax_tpu.models import long_context as lc
    cfg = lc.tiny_config(parallelism="pipeline", num_layers=8,
                         num_microbatches=4)
    info = dict(lc.build_model(cfg).pipeline_info)
    # the model declares the schedule; the batch the drivers feed it
    # (B=32, T=16) sets the boundary activation: tokens x dim x 4B
    info["global_batch"] = 32
    info["act_bytes"] = 32 * 16 * cfg.model_dim * 4
    return info


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="also write the JSON to this path")
    ap.add_argument("--n_chips", type=int, default=8)
    ap.add_argument("--batch_per_chip", type=int, default=128)
    ap.add_argument("--table_dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--dedup_capacity", default=None,
                    help="per-device unique-id slots: an int, or 'auto' "
                         "for per-table capacities from the measured "
                         "distinct-id profile")
    ap.add_argument("--pipeline", action="store_true",
                    help="append the per-plan pipeline wire section "
                         "(inter-stage bytes + bubble per pp>1 plan)")
    args = ap.parse_args()
    cap = args.dedup_capacity
    if cap is not None and cap != "auto":
        cap = int(cap)
    result = flagship_accounting(args.n_chips, args.batch_per_chip,
                                 table_dtype=args.table_dtype,
                                 dedup_capacity=cap)
    if args.pipeline:
        result["pipeline_plans"] = pipeline_plan_section(
            _demo_pipeline_record(), num_devices=args.n_chips)
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
