"""Flagship wire-bytes accounting: sparse path vs dense all-reduce.

The BASELINE.json north-star secondary metric is "sparse-grad bytes on
wire" — the reference's PS win is shipping only the touched (ids, rows)
of the 793k-vocab embedding/softmax tables instead of dense [V, D]
gradients (reference: graph_transform_lib.py:1041-1211). The accounting
is trace-time (ops/embedding.py records per-lookup wire terms while the
step traces), so the REAL flagship config can be measured anywhere: this
script abstractly evaluates the full hybrid training step (no parameter
allocation, no execution) on an 8-virtual-device CPU mesh and prints the
accounting as one JSON line.

Run: python tools/wire_bytes_report.py [--out WIRE_BYTES.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count"
                                 "=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def flagship_accounting(n_chips: int = 8, batch_per_chip: int = 128,
                        num_steps: int = 20):
    """Build the bench's flagship engine (793,470-vocab LM1B, HYBRID,
    slices mode) and return its wire-bytes accounting from an abstract
    trace of one training step."""
    import jax
    import numpy as np

    from parallax_tpu.common.config import ParallaxConfig
    from parallax_tpu.core import engine as engine_lib, mesh as mesh_lib
    from parallax_tpu.models import lm1b

    devices = jax.devices()[:n_chips]
    mesh = mesh_lib.build_mesh(devices, num_partitions=n_chips)
    cfg = lm1b.LM1BConfig(num_partitions=n_chips,
                          sparse_grad_mode="slices")
    model = lm1b.build_model(cfg)
    batch = lm1b.make_batch(np.random.default_rng(0),
                            batch_per_chip * n_chips, num_steps,
                            cfg.vocab_size)
    config = ParallaxConfig(run_option="HYBRID", search_partitions=False,
                            sparse_grad_mode="slices")
    eng = engine_lib.Engine(model, mesh, config, batch)

    # Abstract evaluation: traces the step (filling the per-lookup wire
    # records) without allocating the 793k-vocab tables or running math.
    abstract_state = jax.eval_shape(eng._init_jit, 0)
    abstract_batch = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                      for k, v in eng.shard_batch(batch).items()}
    with eng.mesh:
        jax.eval_shape(eng._step_jit, abstract_state, abstract_batch)
    wire = eng.sparse_wire_bytes_per_step()
    return {
        "config": {
            "model": "lm1b", "vocab_size": cfg.vocab_size,
            "emb_dim": cfg.emb_dim, "proj_dim": cfg.proj_dim,
            "batch_size": batch_per_chip * n_chips,
            "num_steps": num_steps, "n_chips": n_chips,
            "run_option": "HYBRID", "sparse_grad_mode": "slices",
        },
        **wire,
        "sparse_over_dense": (wire["sparse_path_bytes"]
                              / wire["dense_allreduce_bytes"]
                              if wire.get("dense_allreduce_bytes")
                              else None),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="also write the JSON to this path")
    ap.add_argument("--n_chips", type=int, default=8)
    ap.add_argument("--batch_per_chip", type=int, default=128)
    args = ap.parse_args()
    result = flagship_accounting(args.n_chips, args.batch_per_chip)
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
