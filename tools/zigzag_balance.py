"""Zig-zag vs contiguous causal ring attention: the decision artifact.

VERDICT r3 item 8. Two measurements:

1. **Analytic per-rotation wall model** (what multi-chip hardware will
   see): every ring rotation is barriered by the K/V ppermute, so the
   rotation's wall time is the SLOWEST device's tile work.
   - contiguous + causal-skip: device i computes a full tile in the
     first i+1 rotations and idles in the rest — but device n-1 computes
     in ALL n rotations, so the wall is n full tiles while the average
     device does (n+1)/2: utilization (n+1)/(2n) -> 1/2 as n grows.
   - zigzag (ops/ring_attention.py fast path): the self rotation is one
     full tile, every other rotation is a maskless HALF tile on every
     device: wall = 1 + (n-1)/2 tiles at 100% utilization.

2. **Single-host sanity run** (8 virtual CPU devices): numeric parity of
   both placements against unsharded full attention, plus wall-clock.
   A serialized host executes the SUM of all devices' work, which the
   analytic model says is equal (n(n+1)/2 tiles both ways), so the CPU
   times should be ~equal — the hardware win is the per-rotation max,
   not the sum. (Before the half-tile fast path, zigzag cost n^2 tiles
   total and measured ~1.8x SLOWER here; equal CPU time is the signal
   the placement now costs nothing to turn on.)

Run: python tools/zigzag_balance.py [--out perf/zigzag_balance.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count"
                                 "=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def analytic(n: int) -> dict:
    contiguous_wall = float(n)          # device n-1 computes every rotation
    zigzag_wall = 1.0 + (n - 1) / 2.0   # self tile + maskless half tiles
    return {
        "ring_size": n,
        "contiguous_wall_tiles": contiguous_wall,
        "contiguous_utilization": (n + 1) / (2.0 * n),
        "zigzag_wall_tiles": zigzag_wall,
        "zigzag_utilization": 1.0,
        "projected_attention_speedup": contiguous_wall / zigzag_wall,
    }


def measure(B=2, T=2048, H=4, D=64, iters=10) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from parallax_tpu.ops.ring_attention import (
        full_attention_reference, inverse_zigzag_permutation,
        ring_attention, zigzag_permutation)

    n = jax.device_count()
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    rng = np.random.default_rng(0)
    qkv = [jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
           for _ in range(3)]
    want = full_attention_reference(*qkv, causal=True)

    out = {"devices": n, "B": B, "T": T, "H": H, "D": D}
    perm = zigzag_permutation(T, n)
    inv = inverse_zigzag_permutation(T, n)
    for placement in ("contiguous", "zigzag"):
        if placement == "zigzag":
            args = [x[:, perm] for x in qkv]
        else:
            args = qkv
        fn = jax.jit(lambda q, k, v, p=placement: ring_attention(
            q, k, v, mesh, "sp", causal=True, placement=p))
        got = fn(*args)
        got = got[:, inv] if placement == "zigzag" else got
        err = float(jnp.max(jnp.abs(got - want)))
        assert err < 2e-4, (placement, err)
        jax.block_until_ready(fn(*args))
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(*args)
        jax.block_until_ready(r)
        out[f"{placement}_host_ms"] = round(
            (time.perf_counter() - t0) / iters * 1e3, 2)
        out[f"{placement}_max_abs_err"] = err
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    # key renamed from 'cpu_sanity' (r4): a serialized host executes the
    # SUM of per-device work, which is equal under both placements, so
    # these timings cannot confirm the balance win — they are a PARITY
    # check only (VERDICT r4 weak item 5). The zigzag decision rests on
    # the analytic per-rotation-max model; the host_ms fields are
    # incidental and the win is only measurable on parallel hardware.
    parity = measure()
    parity["note"] = ("numerics parity only; serialized-host timings "
                      "cannot evidence the balance win (equal total "
                      "work both ways)")
    result = {"analytic_n8": analytic(8), "analytic_n64": analytic(64),
              "cpu_parity_check": parity}
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
