"""Serving latency attribution: name the dominant cause per percentile.

Reads the per-request lifecycle records (obs/reqtrace.py) a serving
session or fleet collected and answers the question flat histograms
cannot: *which phase* makes p99 slow — "p99 is slot_wait-bound at 64
offered", not "p99 is 885 ms". Requests are bucketed by TTFT percentile
band (p50 = the typical half, p90 = the 50-90 band, p99 = the tail) and
each bucket reports its mean phase shares and the dominant phase.

Used three ways:

* ``analyze(records)`` — pure function over record snapshots
  (``session.request_records()`` / ``fleet.request_records()`` / the
  ``request_records`` section of a flight artifact).
* ``measure(level=64, ...)`` — bring up the tiny-NMT continuous-decode
  rig at one offered-concurrency level and report attribution for it
  (the tier-1 acceptance path: the 64-offered level must name a
  dominant p99 cause).
* CLI::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/serve_report.py --level 64

bench.py stamps the same analysis (via tools/loadgen.py sweep rows)
into the ``serve.continuous`` block — ``ttft_decomp`` shares,
``deadline_miss_budget_consumed`` and the per-percentile report whose
p99 keys tools/check_regression.py secondary-gates. All numbers are
CPU-relative off-TPU, like every serving latency in this repo.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Dict, List, Optional, Sequence

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from parallax_tpu.obs.metrics import nearest_rank  # noqa: E402

# percentile bands, keyed by their upper edge
BANDS = (("p50", 0.0, 0.50), ("p90", 0.50, 0.90), ("p99", 0.90, 1.01))


def ttft_shares(records: Sequence[Dict]) -> Optional[Dict[str, float]]:
    """Mean share of TTFT per phase across completed records (the
    ``ttft_decomp`` block bench.py stamps); None when no record
    carries a decomposition."""
    totals: Dict[str, float] = {}
    grand = 0.0
    for r in records:
        dec = r.get("ttft_decomp")
        if not dec:
            continue
        for k, v in dec.items():
            totals[k] = totals.get(k, 0.0) + v
            grand += v
    if grand <= 0:
        return None
    return {k.replace("_ms", "_share"): round(v / grand, 4)
            for k, v in sorted(totals.items())}


def deadline_miss_budget_consumed(records: Sequence[Dict],
                                  budget: float = 0.01
                                  ) -> Optional[float]:
    """Window deadline-miss rate over the SLO budget (1.0 = the whole
    budget burned); None when no record carried a deadline."""
    with_ddl = [r for r in records if r.get("deadline_ms") is not None]
    if not with_ddl:
        return None
    missed = sum(
        1 for r in with_ddl
        if r.get("outcome") == "deadline_exceeded"
        or (r.get("total_ms") or 0) > r["deadline_ms"])
    return round((missed / len(with_ddl)) / budget, 4)


def analyze(records: Sequence[Dict], metric: str = "ttft_ms") -> Dict:
    """Bucket records by ``metric`` percentile band; per bucket, the
    mean phase shares (from each record's TTFT decomposition) and the
    DOMINANT phase. Returns a JSON-ready report; ``dominant_p99`` is
    the headline ("p99 is <phase>-bound")."""
    rows = [r for r in records
            if r.get(metric) is not None and r.get("ttft_decomp")]
    rows.sort(key=lambda r: r[metric])
    vals = [r[metric] for r in rows]
    buckets: Dict[str, Dict] = {}
    n = len(rows)
    for name, lo, hi in BANDS:
        lo_i, hi_i = int(math.floor(lo * n)), int(math.ceil(hi * n))
        band = rows[lo_i:min(hi_i, n)]
        if not band:
            buckets[name] = None
            continue
        shares = ttft_shares(band) or {}
        dominant = (max(shares, key=shares.get).replace("_share", "")
                    if shares else None)
        totals = sorted(v for r in band
                        if (v := r.get("total_ms")) is not None)
        buckets[name] = {
            "count": len(band),
            # the band's upper-edge latency (the gated key: p99 TTFT)
            "ttft_ms": round(nearest_rank(vals, min(hi, 1.0)), 3),
            "total_ms": (round(totals[-1], 3) if totals else None),
            "shares": shares,
            "dominant": dominant,
        }
    p99 = buckets.get("p99") or {}
    return {
        "metric": metric,
        "requests_analyzed": n,
        "buckets": buckets,
        "dominant_p99": p99.get("dominant"),
    }


def headline(report: Dict, offered: Optional[int] = None) -> str:
    """One sentence: 'p99 is <phase>-bound (...)'. """
    dom = report.get("dominant_p99")
    if dom is None:
        return "no completed requests to attribute"
    p99 = report["buckets"]["p99"]
    at = f" at {offered} offered" if offered else ""
    return (f"p99 is {dom}-bound{at} "
            f"({p99['shares'].get(dom + '_share', 0) * 100:.0f}% of "
            f"TTFT; p99 ttft {p99['ttft_ms']}ms)")


def measure(level: int = 64, requests: Optional[int] = None,
            slots: Optional[int] = None, T: int = 8, Ts: int = 6,
            model_dim: int = 16, vocab: int = 64,
            deadline_ms: Optional[float] = None,
            speculative: bool = False,
            prefill_chunk_layers=None) -> dict:
    """One offered-concurrency level end to end on the tiny-NMT
    continuous-decode rig; returns the attribution report plus the
    trace-derived serve keys. Small model defaults keep the 64-offered
    acceptance level tier-1-affordable on CPU."""
    from tools import loadgen

    n_req = requests or max(2 * level, 16)
    sess, make_feed = loadgen.demo_decode_session(
        slots=(slots or level), T=T, Ts=Ts, model_dim=model_dim,
        vocab=vocab, speculative=speculative,
        prefill_chunk_layers=prefill_chunk_layers)
    try:
        rep = loadgen.run_load(sess, make_feed, n_req,
                               concurrency=level,
                               deadline_ms=deadline_ms)
        records = sess.request_records()
    finally:
        sess.close()
    report = analyze(records)
    return {
        "offered_concurrency": level,
        "requests": n_req,
        "completed": rep["completed"],
        "ttft_ms": rep["ttft_ms"],
        "latency_ms": rep["latency_ms"],
        "report": report,
        "headline": headline(report, offered=level),
        "ttft_decomp": ttft_shares(records),
        "deadline_miss_budget_consumed":
            deadline_miss_budget_consumed(records),
        "records_sample": records[:3],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--level", type=int, default=64,
                    help="offered concurrency (slots == clients)")
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--records", type=str, default=None,
                    help="analyze a JSON file of record snapshots (a "
                         "flight artifact's request_records section) "
                         "instead of running the rig")
    args = ap.parse_args(argv)
    if args.records:
        with open(args.records) as f:
            doc = json.load(f)
        records = doc.get("request_records", doc) \
            if isinstance(doc, dict) else doc
        report = analyze(records)
        out = {"report": report, "headline": headline(report)}
    else:
        out = measure(level=args.level, requests=args.requests)
    print(json.dumps(out, indent=2, default=str))
    ok = (out["report"]["dominant_p99"] is not None)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
