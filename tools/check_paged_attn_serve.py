"""Paged-attention serve guard: kernel executor == einsum executor,
token for token, with zero serve-time compiles and zero leaked pages.

ISSUE 16 acceptance, enforced in tier-1
(tests/test_paged_attn.py::test_paged_attn_serve_guard) and runnable
directly::

    JAX_PLATFORMS=cpu python tools/check_paged_attn_serve.py

Two sessions over the FULL high-concurrency rig (paged KV pool +
chunked prefill + speculative decoding, tools/loadgen.py) fed the
EXACT same deterministic request stream — one with
``attn_impl='einsum'`` (the full-width gather), one with
``attn_impl='kernel'`` (the fused Pallas decode kernel,
ops/pallas_paged_attention; interpret mode off-TPU). Three contracts:

* **exact tokens** — every request's output stream is identical under
  both executors: the kernel is an HBM-traffic optimization, never a
  result change. The rig pins ``compute_dtype=float32``, where the
  token-identity contract is exact (under bf16 the two executors
  differ within rounding noise — see the module docstring of
  ops/pallas_paged_attention).
* **closed signature set** — the kernel path resolves INSIDE the
  existing step/verify traces, so the jitted signature set is
  unchanged: the ``jax.monitoring`` backend-compile witness (activated
  after session construction, when AOT warmup has legitimately
  compiled everything) stays at 0 across both sessions, and
  ``serve.recompiles`` stays 0.
* **zero leaked pages** — after close, both sessions' pool allocators
  report ``in_use == 0``: the executor switch cannot change page
  accounting (it only changes how pages are READ).

A second, mid-churn phase re-submits half the stream against the
kernel session (slots refill, pages recycle through the free list) and
re-diffs against the einsum session's same re-submission — stale-page
reuse must stay invisible through the kernel's in-kernel masking
exactly as it is through clip-then-mask.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_compile_events = {"n": 0, "active": False}


def _install_listener():
    import jax

    def _listen(event, duration, **kw):
        if _compile_events["active"] and "backend_compile" in event:
            _compile_events["n"] += 1

    jax.monitoring.register_event_duration_secs_listener(_listen)


def _serve_round(sess, feeds, caps, timeout_s: float = 300.0):
    reqs = [sess.submit(f, max_new_tokens=c)
            for f, c in zip(feeds, caps)]
    return [[int(t) for t in r.result(timeout=timeout_s)]
            for r in reqs]


def _rig(attn_impl: str, slots: int = 4):
    import jax.numpy as jnp

    from tools import loadgen
    return loadgen.demo_decode_session(
        slots=slots, T=12, Ts=8, page_size=4,
        model_dim=32, num_layers=2, vocab=64,
        prefill_chunk_layers=1, spec_tokens=2,
        attn_impl=attn_impl, compute_dtype=jnp.float32)


def measure(n_requests: int = 10) -> dict:
    _install_listener()

    def run_session(attn_impl):
        sess, make_feed = _rig(attn_impl)
        feeds = [make_feed(i) for i in range(n_requests)]
        caps = [7 if i % 2 else 12 for i in range(n_requests)]
        try:
            _compile_events["n"] = 0
            _compile_events["active"] = True
            outs = _serve_round(sess, feeds, caps)
            # churn: re-submit half the stream so slots refill and
            # pages recycle through the free list with stale content
            outs2 = _serve_round(sess, feeds[: n_requests // 2],
                                 caps[: n_requests // 2])
            _compile_events["active"] = False
            stats = sess.stats()
            alloc = sess._scheduler._alloc
            return {"outs": outs, "outs2": outs2,
                    "compiles": _compile_events["n"],
                    "recompiles": stats.get("serve.recompiles", 0),
                    "completed": stats.get("serve.completed", 0),
                    "pages_in_use_after_close": None,
                    "_alloc": alloc}
        finally:
            sess.close()

    ein = run_session("einsum")
    ein["pages_in_use_after_close"] = ein.pop("_alloc").in_use
    ker = run_session("kernel")
    ker["pages_in_use_after_close"] = ker.pop("_alloc").in_use

    mism = sum(1 for a, b in zip(ein["outs"], ker["outs"]) if a != b)
    mism2 = sum(1 for a, b in zip(ein["outs2"], ker["outs2"])
                if a != b)
    return {
        "requests": n_requests,
        "token_mismatches": mism,
        "token_mismatches_churn": mism2,
        "tokens_decoded": sum(len(o) for o in ker["outs"]
                              + ker["outs2"]),
        "einsum": {k: ein[k] for k in
                   ("compiles", "recompiles", "completed",
                    "pages_in_use_after_close")},
        "kernel": {k: ker[k] for k in
                   ("compiles", "recompiles", "completed",
                    "pages_in_use_after_close")},
    }


def check(result: dict) -> list:
    """-> list of violated invariants (empty = pass)."""
    bad = []
    if result["token_mismatches"] != 0:
        bad.append(f"{result['token_mismatches']} request(s) decoded "
                   f"DIFFERENT tokens under attn_impl='kernel' vs "
                   f"'einsum' — the executor changed results")
    if result["token_mismatches_churn"] != 0:
        bad.append(f"{result['token_mismatches_churn']} churn-round "
                   f"mismatch(es) — stale recycled pages leaked "
                   f"through the kernel's masking")
    for name in ("einsum", "kernel"):
        r = result[name]
        if r["compiles"] != 0:
            bad.append(f"{r['compiles']} XLA compile(s) fired during "
                       f"{name}-executor serving — the executor "
                       f"switch leaked a signature past AOT warmup")
        if r["recompiles"] != 0:
            bad.append(f"serve.recompiles = {r['recompiles']} "
                       f"({name} rig)")
        if r["pages_in_use_after_close"] != 0:
            bad.append(f"{r['pages_in_use_after_close']} page(s) "
                       f"leaked after close ({name} rig)")
        if not r["completed"]:
            bad.append(f"no request completed ({name} rig)")
    return bad


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=10)
    args = ap.parse_args(argv)
    result = measure(n_requests=args.requests)
    violations = check(result)
    result["violations"] = violations
    result["ok"] = not violations
    print(json.dumps(result, indent=2, default=str))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
