"""Style gate.

Parity with the reference's tools/style_check.py:22-27 (pycodestyle over
the core, excluding examples). pycodestyle may not be installed in every
image, so fall back to python's compileall as a syntax gate.
"""

import subprocess
import sys


def main() -> int:
    targets = ["parallax_tpu", "tests", "bench.py", "__graft_entry__.py"]
    try:
        import pycodestyle  # noqa: F401
        rc = subprocess.call(
            [sys.executable, "-m", "pycodestyle",
             "--max-line-length=100", *targets])
    except ImportError:
        print("pycodestyle not installed; running syntax check only")
        rc = subprocess.call(
            [sys.executable, "-m", "compileall", "-q", *targets])
    return rc


if __name__ == "__main__":
    sys.exit(main())
