"""Fleet chaos guard: crash failover + mid-traffic hot-swap, gated.

ISSUE 7 acceptance, enforced in tier-1
(tests/test_fleet.py::test_fleet_chaos_guard via the established
subprocess-driver pattern) and runnable directly::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/check_fleet_faults.py

Three phases over a 2-replica tiny-NMT continuous-decode fleet
(tools/loadgen.py ``demo_decode_fleet`` — each replica a full
ServeSession with paged KV on its own submesh):

* **baseline** — per-request greedy tokens computed OUTSIDE serving
  (``nmt.greedy_decode``), the bit-identity reference for everything
  below. Greedy decode is deterministic, so any healthy replica — and
  any failover retry — must reproduce it exactly.
* **crash** — the full request set is accepted, then one loaded
  replica is killed mid-flight (serve/faults.py injected crash). The
  contract: ZERO dropped accepted requests (the dead replica's
  accepted-but-unserved work fails over within the original
  deadline), zero late service, zero serve-time recompiles on the
  survivor (``serve.recompiles`` AND a ``jax.monitoring``
  backend-compile witness), every request — retried or not — emitting
  bit-identical tokens to the baseline, and a flight-recorder
  artifact naming the ``fleet_crash`` incident. The paged-KV pages
  held on the dead replica are simply abandoned with it; the retry
  allocates fresh pages on the survivor. ``failover_recovery_ms`` =
  crash injection -> last failed-over request completed.
* **hotswap** — a fresh 2-replica fleet under continuing closed-loop
  load gets ``push_weights`` mid-traffic. The pushed checkpoint is a
  value-identical COPY of the serving params (host round-trip), so
  the rotation machinery — drain, ``swap_params`` on the same mesh,
  re-admission — is fully exercised while the token-identity bar
  stays assertable; a separate unit test
  (tests/test_fleet.py) proves a *different* checkpoint actually
  changes outputs. The contract: zero dropped, zero late, 2 swaps,
  zero recompiles on fresh AND swapped replicas (a post-swap request
  wave re-checks), tokens identical. ``hotswap_blackout_ms`` = the
  longest fleet-wide gap between request completions inside the swap
  window — with >= 2 replicas the fleet must keep completing work
  while each one rotates.

The XLA-compile witness is paused around ``push_weights`` itself (a
``device_put`` of fresh arrays may legitimately build a transfer
program; the zero-recompile claim is about SERVING dispatches, which
``serve.recompiles`` covers end to end and the witness re-arms for).

bench.py stamps the ``bench`` sub-dict as the ``serve.fleet`` block;
tools/check_regression.py gates ``failover_recovery_ms`` and
``hotswap_blackout_ms`` between harness-compatible rounds. All
numbers are CPU-relative until the TPU relay appears.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_compile_events = {"n": 0, "active": False}


def _install_listener():
    import jax

    def _listen(event, duration, **kw):
        if _compile_events["active"] and "backend_compile" in event:
            _compile_events["n"] += 1

    jax.monitoring.register_event_duration_secs_listener(_listen)


def _baseline_tokens(params, cfg, make_feed, n: int, max_len: int):
    """Reference greedy tokens per request, computed outside serving."""
    import numpy as np

    from parallax_tpu.models import nmt

    out = []
    for i in range(n):
        src = make_feed(i)["src"]
        ref = np.asarray(nmt.greedy_decode(
            params, cfg, src[None], max_len=max_len))[0].tolist()
        if nmt.EOS_ID in ref:
            ref = ref[:ref.index(nmt.EOS_ID) + 1]
        out.append(ref)
    return out


def _await_all(reqs, timeout_s=300.0):
    """Collect every future's outcome; returns (dropped, late,
    completions) where completions maps index -> (tokens, t_done,
    replicas)."""
    dropped, late, done = [], [], {}
    for i, r in enumerate(reqs):
        try:
            toks = r.result(timeout=timeout_s)
        except Exception as e:
            dropped.append((i, f"{type(e).__name__}: {e}"))
            continue
        if r.deadline is not None and r.t_done > r.deadline:
            late.append(i)
        done[i] = (list(toks), r.t_done, list(r.replicas))
    return dropped, late, done


def _mismatches(done, baseline):
    bad = []
    for i, (toks, _t, _reps) in done.items():
        if toks != baseline[i]:
            bad.append({"request": i, "got": toks,
                        "want": baseline[i]})
    return bad


def measure(n_requests: int = 20, slots: int = 4, T: int = 12,
            Ts: int = 6, deadline_ms: float = 120000.0,
            model_dim: int = 32, vocab: int = 64) -> dict:
    import numpy as np

    from parallax_tpu.obs.flightrec import FlightRecorder
    from parallax_tpu.serve import FaultInjector
    from tools import loadgen

    _install_listener()
    flight_dir = tempfile.mkdtemp(prefix="fleet_flight_")
    result: dict = {"flight_dir": flight_dir}

    # -- phase 1+2: crash failover under load --------------------------
    inj = FaultInjector()
    flight = FlightRecorder(flight_dir=flight_dir)
    fleet, make_feed, params, cfg = loadgen.demo_decode_fleet(
        replicas=2, slots=slots, T=T, Ts=Ts, model_dim=model_dim,
        vocab=vocab, faults=inj, flight=flight)
    baseline = _baseline_tokens(params, cfg, make_feed, n_requests, T)
    try:
        _compile_events["n"] = 0
        _compile_events["active"] = True
        reqs = [fleet.submit(make_feed(i), deadline_ms=deadline_ms)
                for i in range(n_requests)]
        # let the fleet get properly in flight, then kill the replica
        # carrying the most work
        while sum(1 for r in reqs if r.done()) < max(2, n_requests // 8):
            time.sleep(0.005)
        router = fleet._router
        victim = max((h for h in router.handles() if h.session.alive),
                     key=lambda h: h.session.load())
        t_crash = time.perf_counter()
        inj.arm(victim.rid, "crash")
        dropped, late, done = _await_all(reqs)
        _compile_events["active"] = False
        retried = {i: v for i, v in done.items() if len(v[2]) > 1}
        mism1 = _mismatches(done, baseline)
        recovery_ms = (max((v[1] for v in retried.values()),
                           default=t_crash) - t_crash) * 1e3
        stats = fleet.stats()
        result["crash"] = {
            "requests": n_requests,
            "victim_replica": victim.rid,
            "dropped": len(dropped),
            "dropped_detail": dropped[:5],
            "late": len(late),
            "completed": len(done),
            "retried_requests": len(retried),
            "failovers": stats.get("fleet.failovers", 0),
            "ejections": stats.get("fleet.ejections", 0),
            "token_mismatch_count": len(mism1),
            "token_mismatches": mism1[:5],  # detail only; count above
            "recompiles": fleet.recompiles(),
            "serve_time_xla_compiles": _compile_events["n"],
            "failover_recovery_ms": round(recovery_ms, 3),
            "replica_states": {k: v["state"] for k, v in
                               stats["replicas"].items()},
        }
    finally:
        fleet.close()
    crash_artifacts = [p for p in flight.dump_paths
                      if "fleet_crash" in os.path.basename(p)]
    result["crash"]["flight_artifacts"] = crash_artifacts
    # request forensics (ISSUE 12): every completed request's TTFT
    # decomposition must sum to its measured client-side TTFT (the
    # phase machine partitions the client window by construction, so
    # a drift here means a phase is being dropped or double-counted)
    ttft_errs = []
    for r in reqs:
        rec = getattr(r, "rec", None)
        if rec is None or r.t_first_token is None \
                or rec.ttft_decomp is None:
            continue
        client_ttft_ms = (r.t_first_token - r.t_enqueue) * 1e3
        decomp_sum = sum(rec.ttft_decomp.values())
        if client_ttft_ms > 0:
            ttft_errs.append(abs(decomp_sum - client_ttft_ms)
                             / client_ttft_ms)
    result["crash"]["ttft_decomp_checked"] = len(ttft_errs)
    result["crash"]["ttft_decomp_max_rel_err"] = (
        round(max(ttft_errs), 5) if ttft_errs else None)
    # the correlated incident artifact: ONE dump that names the
    # crashed replica, stamps a shared incident id, captures router
    # health + circuit-breaker states and the in-flight table, and
    # lists every affected request with its failover hop trail
    incident = {}
    if crash_artifacts:
        with open(crash_artifacts[0]) as f:
            doc = json.load(f)
        det = doc.get("detail") or {}
        affected = det.get("affected_requests") or []
        by_id = {a.get("id"): a.get("hops") or [] for a in affected}
        retried_ids = [r.id for r in reqs if len(r.replicas) > 1]
        incident = {
            "incident_id": doc.get("incident_id"),
            "replica_named": det.get("replica"),
            "affected_count": len(affected),
            "affected_sample": affected[:5],
            "has_router_section": isinstance(doc.get("router"), list),
            "has_inflight_table": isinstance(
                doc.get("requests_in_flight"), list),
            "has_fleet_section": isinstance(doc.get("fleet"), dict),
            "retried_ids": retried_ids,
            "retried_ids_covered": all(
                rid_ in by_id
                and victim.rid in by_id[rid_]
                and len(by_id[rid_]) > 1
                for rid_ in retried_ids),
        }
    result["crash"]["incident"] = incident

    # -- phase 3: mid-traffic weight hot-swap --------------------------
    flight2 = FlightRecorder(flight_dir=flight_dir)
    fleet2, make_feed, params, cfg = loadgen.demo_decode_fleet(
        replicas=2, slots=slots, T=T, Ts=Ts, model_dim=model_dim,
        vocab=vocab, flight=flight2)
    # a value-identical checkpoint via host round-trip: exercises the
    # full rotation machinery while keeping tokens assertable
    import jax
    pushed = jax.tree.map(lambda x: np.array(x), params)
    try:
        _compile_events["n"] = 0
        _compile_events["active"] = True
        reqs2 = []
        stop = threading.Event()

        def client(k):
            i = k
            while i < n_requests and not stop.is_set():
                reqs2.append(fleet2.submit(make_feed(i),
                                           deadline_ms=deadline_ms))
                i += 4

        threads = [threading.Thread(target=client, args=(k,),
                                    daemon=True) for k in range(4)]
        for t in threads:
            t.start()
        while sum(1 for r in list(reqs2) if r.done()) < 2:
            time.sleep(0.005)
        _compile_events["active"] = False  # device_put may compile a
        t_swap0 = time.perf_counter()      # transfer program
        outcome = fleet2.push_weights(pushed)
        t_swap1 = time.perf_counter()
        _compile_events["active"] = True
        for t in threads:
            t.join(timeout=300.0)
        # post-swap wave: swapped executables must serve compile-free
        wave = [fleet2.submit(make_feed(i), deadline_ms=deadline_ms)
                for i in range(n_requests)]
        dropped2, late2, done2 = _await_all(list(reqs2) + wave)
        _compile_events["active"] = False
        # blackout: longest completion gap fleet-wide inside the swap
        # window (edges included — an empty window reads as the whole)
        times = sorted(t for _i, (_tk, t, _r) in done2.items()
                       if t_swap0 <= t <= t_swap1)
        marks = [t_swap0] + times + [t_swap1]
        blackout_ms = max(b - a for a, b in zip(marks, marks[1:])) * 1e3
        all_reqs = list(reqs2) + wave
        # reference per request by replaying its OWN (padded) feed —
        # the submit order across client threads is nondeterministic
        mism = _hotswap_mismatches(done2, all_reqs, params, cfg, T)
        stats2 = fleet2.stats()
        result["hotswap"] = {
            "requests": len(all_reqs),
            "dropped": len(dropped2),
            "dropped_detail": dropped2[:5],
            "late": len(late2),
            "completed": len(done2),
            "outcome": {str(k): v for k, v in outcome.items()},
            "hotswaps": stats2.get("fleet.hotswaps", 0),
            "hotswap_failures": stats2.get("fleet.hotswap_failures", 0),
            "drain_seconds": stats2.get("fleet.drain_seconds"),
            "token_mismatch_count": len(mism),
            "token_mismatches": mism[:5],  # detail only; count above
            "recompiles": fleet2.recompiles(),
            "serve_time_xla_compiles": _compile_events["n"],
            "hotswap_blackout_ms": round(blackout_ms, 3),
            "swap_window_ms": round((t_swap1 - t_swap0) * 1e3, 3),
        }
    finally:
        fleet2.close()

    c, h = result["crash"], result["hotswap"]
    result["bench"] = {
        "replicas": 2,
        "failover_recovery_ms": c["failover_recovery_ms"],
        "hotswap_blackout_ms": h["hotswap_blackout_ms"],
        "failovers": c["failovers"],
        "hotswaps": h["hotswaps"],
        "dropped": c["dropped"] + h["dropped"],
        "late": c["late"] + h["late"],
        "recompiles": c["recompiles"] + h["recompiles"],
        "token_mismatches": (c["token_mismatch_count"]
                             + h["token_mismatch_count"]),
        "incident_correlated": bool(
            c.get("incident", {}).get("incident_id")
            and c["incident"].get("retried_ids_covered")),
        "ttft_decomp_max_rel_err": c.get("ttft_decomp_max_rel_err"),
    }
    return result


def _hotswap_mismatches(done, reqs, params, cfg, max_len):
    """Reference tokens per completed request by replaying its OWN
    feed through standalone greedy decode (the pushed checkpoint is
    value-identical, so one reference serves pre- and post-swap)."""
    import numpy as np

    from parallax_tpu.models import nmt

    bad = []
    for i, (toks, _t, _reps) in done.items():
        src = np.asarray(reqs[i].feed["src"])
        src = src[src != 0] if src.ndim == 1 else src
        ref = np.asarray(nmt.greedy_decode(
            params, cfg, src[None], max_len=max_len))[0].tolist()
        if nmt.EOS_ID in ref:
            ref = ref[:ref.index(nmt.EOS_ID) + 1]
        if list(toks) != ref:
            bad.append({"request": i, "got": list(toks), "want": ref})
    return bad


def check(result: dict) -> list:
    """-> list of violated invariants (empty = pass)."""
    bad = []
    c = result["crash"]
    if c["dropped"]:
        bad.append(f"crash phase dropped {c['dropped']} accepted "
                   f"request(s): {c['dropped_detail']}")
    if c["late"]:
        bad.append(f"crash phase served {c['late']} request(s) late")
    if c["completed"] != c["requests"]:
        bad.append(f"crash phase completed {c['completed']}/"
                   f"{c['requests']}")
    if c["retried_requests"] == 0:
        bad.append("the injected crash caused no failover — the chaos "
                   "harness did not exercise the contract")
    if c["token_mismatch_count"]:
        bad.append(f"failover broke token identity on "
                   f"{c['token_mismatch_count']} request(s): "
                   f"{c['token_mismatches']}")
    if c["recompiles"] != 0:
        bad.append(f"crash phase serve.recompiles = {c['recompiles']}")
    if c["serve_time_xla_compiles"] != 0:
        bad.append(f"{c['serve_time_xla_compiles']} XLA compile(s) "
                   f"during crash-phase serving")
    if not c["flight_artifacts"]:
        bad.append("no flight-recorder artifact names the fleet_crash "
                   "incident")
    inc = c.get("incident") or {}
    if c["flight_artifacts"]:
        if not inc.get("incident_id"):
            bad.append("fleet_crash artifact carries no incident_id")
        if inc.get("replica_named") != c["victim_replica"]:
            bad.append(
                f"fleet_crash artifact names replica "
                f"{inc.get('replica_named')!r}, not the crashed "
                f"{c['victim_replica']!r}")
        if not inc.get("retried_ids_covered"):
            bad.append(
                f"fleet_crash artifact's affected_requests does not "
                f"cover every failed-over request with its hop trail "
                f"(retried={inc.get('retried_ids')}, "
                f"affected={inc.get('affected_sample')})")
        for section in ("has_router_section", "has_inflight_table",
                        "has_fleet_section"):
            if not inc.get(section):
                bad.append(f"fleet_crash artifact missing correlated "
                           f"section: {section[4:]}")
    if not c.get("ttft_decomp_checked"):
        bad.append("no per-request TTFT decompositions were available "
                   "to verify")
    elif c["ttft_decomp_max_rel_err"] > 0.05:
        bad.append(
            f"per-request TTFT decomposition drifts "
            f"{c['ttft_decomp_max_rel_err'] * 100:.2f}% from the "
            f"measured client-side TTFT (> 5%)")
    h = result["hotswap"]
    if h["dropped"]:
        bad.append(f"hot-swap phase dropped {h['dropped']} accepted "
                   f"request(s): {h['dropped_detail']}")
    if h["late"]:
        bad.append(f"hot-swap phase served {h['late']} request(s) late")
    if h["hotswaps"] != 2 or h["hotswap_failures"]:
        bad.append(f"expected 2 clean hot-swaps, got "
                   f"{h['hotswaps']} ({h['hotswap_failures']} failed)")
    if h["token_mismatch_count"]:
        bad.append(f"hot-swap broke token identity on "
                   f"{h['token_mismatch_count']} request(s): "
                   f"{h['token_mismatches']}")
    if h["recompiles"] != 0:
        bad.append(f"hot-swap phase serve.recompiles = "
                   f"{h['recompiles']} — the swap invalidated the AOT "
                   f"executable set")
    if h["serve_time_xla_compiles"] != 0:
        bad.append(f"{h['serve_time_xla_compiles']} XLA compile(s) "
                   f"during hot-swap-phase serving")
    return bad


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=20)
    ap.add_argument("--slots", type=int, default=4)
    args = ap.parse_args(argv)
    result = measure(n_requests=args.requests, slots=args.slots)
    violations = check(result)
    result["violations"] = violations
    result["ok"] = not violations
    print(json.dumps(result, indent=2, default=str))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
