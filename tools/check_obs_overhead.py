"""Micro-bench: the observability layer must cost <=2% of step wall-time.

ISSUE 2 acceptance (extended by ISSUEs 5, 13, 17 and 20): the
always-on instrumentation — spans + metrics registry, the per-step
timeline attribution row, the step-time anomaly detector, the plan
observatory's per-step memwatch sample and idle profile-hook bracket,
the numerics observatory at its default sampling duty cycle (one
consume per sampled step + one skip per off-step), and the ops
observatory's per-step terms (one goodput-ledger fold, one throttled
alert poll, the amortized interval rule pass, journal emits at their
measured event rate)
— on the simple-model step loop stays within 2% of the
uninstrumented loop. ISSUE 17's killswitch claim is STRUCTURAL and
asserted on a second mini-session built under ``obs.disable()``:
``PARALLAX_OBS=0`` means zero extra step outputs (no ``numerics`` key
in the output dict at all) and no consumer/replay machinery
constructed (``sess.numerics is None``). The flight
recorder does NO per-step work (it dumps bounded rings other
components already fill), so it has no term here; what is asserted for
it (and the rest) is the kill switch: with ``obs.disable()`` the
timeline row and the anomaly observation must not happen at all
(``killswitch_clean``). Run directly::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/check_obs_overhead.py

or via tier-1 (tests/test_obs.py::test_obs_overhead_within_budget).

Methodology — why not a plain A/B wall-clock diff: on a shared CI box
the step-to-step wall time swings far more than 2% (measured ±10-20%
between adjacent 15-step windows on the committed rig), so a direct
subtraction would be pure noise at the tolerance being enforced. The
obs layer, however, is *purely additive host-side code* on the dispatch
path — instrumented time = uninstrumented time + (obs instrument
executions × unit cost) — so the enforced number decomposes exactly:

  1. run the real instrumented loop and COUNT the per-step instrument
     executions from the layer itself (span events recorded, histogram
     samples, counter increments — auto-adapts when instrumentation is
     added or removed);
  2. micro-bench each unit cost (min over many tight batches: minima
     are robust to contention, which only ever adds time);
  3. overhead = (counts x unit costs + the per-step batch-signature
     check) / median step wall-time.

The raw interleaved A/B comparison is still measured and reported
(``ab_overhead_frac``) for eyeballing on a quiet machine; the asserted
bound is the decomposed ``overhead_frac``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _unit_cost_us(fn, iters: int = 2000, batches: int = 7) -> float:
    """Cost of fn() in microseconds: min over several tight batches."""
    best = float("inf")
    for _ in range(batches):
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e6


def measure(steps: int = 60, batch: int = 256, ab_segments: int = 12,
            seg_steps: int = 15) -> dict:
    import numpy as np

    import parallax_tpu as parallax
    from parallax_tpu import obs
    from parallax_tpu.obs import trace
    from parallax_tpu.models import simple

    # numerics_interval=4 puts the ISSUE-17 observatory on the priced
    # rig at its documented default-sampling duty cycle (every 4th
    # step pays one in-graph stats tree + one host consume); the
    # auto-enabled monitor_health rides along and is counted by the
    # same span/hist/inc accounting as everything else
    sess, *_ = parallax.parallel_run(
        simple.build_model(learning_rate=0.1),
        parallax_config=parallax.Config(run_option="AR",
                                        search_partitions=False,
                                        numerics_interval=4))
    rng = np.random.default_rng(0)
    batches = [simple.make_batch(rng, batch) for _ in range(8)]
    try:
        for i in range(20):  # compile + warm caches
            sess.run("loss", feed_dict=batches[i % 8])

        # -- 1. instrumented loop: count real per-step executions ------
        collector = trace.get_collector()
        collector.clear()
        before = sess.metrics.snapshot()
        tl_before = sess.timeline.total_rows
        anom_before = sess.anomaly.total_observed
        nm_before = sess.numerics.total_samples \
            + sess.numerics.total_skipped
        jr_before = sess.journal.seq
        obs.enable()
        times = []
        last = None
        for i in range(steps):
            t0 = time.perf_counter()
            last = sess.run("loss", feed_dict=batches[i % 8])
            times.append(time.perf_counter() - t0)
        float(last)  # drain
        sess.numerics.poll(block=True)  # consume every queued sample
        after = sess.metrics.snapshot()
        nm_consumed_per_step = (sess.numerics.total_samples
                                + sess.numerics.total_skipped
                                - nm_before) / steps
        nm_samples_per_step = 1.0 / sess.numerics.interval
        spans_per_step = len(collector.events()) / steps
        tl_rows_per_step = (sess.timeline.total_rows - tl_before) / steps
        anom_per_step = (sess.anomaly.total_observed
                         - anom_before) / steps
        # ops observatory (ISSUE 20): journal events are lifecycle-rare
        # (this count is ~0 on a healthy loop — priced anyway so a
        # regression that starts emitting per-step shows up here)
        journal_per_step = (sess.journal.seq - jr_before) / steps

        def _count(snap):
            n = 0
            for k, v in snap.items():
                # timeline.* gauges summarize the row ring lazily at
                # snapshot time — their "count" is rows, whose per-step
                # cost is priced separately below (timeline_row_us),
                # not a histogram record
                if k.startswith("timeline."):
                    continue
                if isinstance(v, dict) and "count" in v:
                    n += v["count"]
            return n

        def _incs(snap):
            return sum(v for v in snap.values() if isinstance(v, int))

        hist_per_step = (_count(after) - _count(before)) / steps
        incs_per_step = (_incs(after) - _incs(before)) / steps
        step_us = float(np.median(times)) * 1e6

        # -- 2. unit costs ---------------------------------------------
        def one_span():
            with trace.span("obs-overhead-bench"):
                pass

        reg = obs.MetricsRegistry()
        h = reg.histogram("obs-overhead-bench")
        c = reg.counter("obs-overhead-bench-c")
        span_us = _unit_cost_us(one_span)
        hist_us = _unit_cost_us(lambda: h.record(1.0))
        inc_us = _unit_cost_us(c.inc)
        eng, b0 = sess.engine, batches[0]
        sig_us = _unit_cost_us(lambda: eng._note_batch_signature(b0),
                               iters=500)
        # forensics (ISSUE 5): one timeline attribution row + one
        # step-time anomaly observation per step, unit-costed on
        # standalone instances against realistic values
        tl_bench = obs.StepTimeline(obs.MetricsRegistry(), capacity=256)
        tl_us = _unit_cost_us(lambda: tl_bench.record_step(
            0, 0.0, 1e-3, 1e-4, 1e-4, 1e-4, 5e-4, 0.0))
        am_bench = obs.AnomalyMonitor(obs.MetricsRegistry())
        anom_us = _unit_cost_us(
            lambda: am_bench.observe("bench", 0, 1.0))
        # plan observatory (ISSUE 13): one memwatch sample per step —
        # unit-costed against the REAL backend stats_fn, so the CPU
        # rig prices the stats-less latch (a few polls then an
        # attribute check) and a TPU rig prices the real device poll —
        # plus the idle profile-hook bracket (profile window NOT
        # armed: the steady state every non-profiled step pays)
        mw_bench = obs.MemWatch(obs.MetricsRegistry())
        mw_us = _unit_cost_us(lambda: mw_bench.sample(0))
        from parallax_tpu.profiler import ProfileHook
        ph_bench = ProfileHook(None, 0)
        ph_us = _unit_cost_us(lambda: (ph_bench.before_step(0),
                                       ph_bench.after_step(0)))
        # numerics observatory (ISSUE 17): one FULL consume per
        # sampled step (gauge sets + trail append + anomaly feeds,
        # priced against already-host numpy values so the unit cost is
        # the host work, not a device sync) plus one skip-path consume
        # per off-step. The anomaly observations the consume fires are
        # ALSO counted in anom_per_step above — double-priced, i.e.
        # conservative.
        from parallax_tpu.obs import numwatch
        nm_bench = numwatch.NumericsMonitor(obs.MetricsRegistry(),
                                            interval=1)
        fake_on = {numwatch.SAMPLED_KEY: np.float32(1.0)}
        fake_off = {numwatch.SAMPLED_KEY: np.float32(0.0)}
        for layer in ("w", "b"):
            fake_on[layer] = {s: np.float32(0.1)
                              for s in numwatch.STAT_NAMES}
            fake_off[layer] = {s: np.float32(0.0)
                               for s in numwatch.STAT_NAMES}
        nm_state = {"i": 0}

        def one_numerics_consume():
            nm_bench.observe(nm_state["i"], fake_on)
            nm_state["i"] += 1

        nm_us = _unit_cost_us(one_numerics_consume)
        nm_skip_us = _unit_cost_us(
            lambda: nm_bench.observe(0, fake_off))
        # ops observatory (ISSUE 20): the per-step terms are ONE ledger
        # fold (on_step) and ONE alert-engine poll (clock read +
        # compare — the throttled steady state); journal emits are
        # event-rate-priced (journal_per_step, ~0 when healthy). The
        # full rule pass (alert_eval_us) runs once per alert_interval_s
        # and is amortized over the steps that interval covers.
        jr_bench = obs.EventJournal(capacity=64,
                                    registry=obs.MetricsRegistry())
        journal_emit_us = _unit_cost_us(
            lambda: jr_bench.emit("bench", "tick", n=1))
        led_bench = obs.GoodputLedger(obs.MetricsRegistry())
        led_row = {"step": 0, "wall_ms": 1.0, "data_wait_ms": 0.1}
        ledger_on_step_us = _unit_cost_us(
            lambda: led_bench.on_step(led_row))
        al_bench = obs.AlertEngine(sess.metrics,
                                   rules=obs.builtin_rules(),
                                   interval_s=3600.0)
        alert_poll_us = _unit_cost_us(al_bench.poll)
        alert_eval_us = _unit_cost_us(al_bench.evaluate, iters=200,
                                      batches=5)
        evals_per_step = (step_us * 1e-6) \
            / float(sess._config.alert_interval_s)

        obs_us = (spans_per_step * span_us + hist_per_step * hist_us
                  + incs_per_step * inc_us + sig_us
                  + tl_rows_per_step * tl_us + anom_per_step * anom_us
                  + mw_us + ph_us
                  + nm_samples_per_step * nm_us
                  + (1.0 - nm_samples_per_step) * nm_skip_us
                  + journal_per_step * journal_emit_us
                  + ledger_on_step_us + alert_poll_us
                  + evals_per_step * alert_eval_us)
        overhead_frac = obs_us / step_us

        # kill switch: disabled, the forensics layer must not collect
        # (the flight recorder has no per-step path at all; its dump
        # triggers are incident-only). The memwatch check runs
        # against an ALWAYS-REPORTING fake stats source: the claim is
        # structural — disabled means no stats poll and no ring
        # growth even when there would be data to collect.
        fake_stats = {"tpu:0": {"bytes_in_use": 10,
                                "peak_bytes_in_use": 12,
                                "bytes_limit": 100}}
        mw_ring = obs.MemWatch(obs.MetricsRegistry(),
                               stats_fn=lambda: dict(fake_stats))
        mw_ring.sample(0)
        obs.disable()
        try:
            n_tl = tl_bench.total_rows
            n_am = am_bench.total_observed
            n_mw = mw_ring.total_samples
            tl_bench.record_step(1, 0.0, 1e-3)
            am_bench.observe("bench", 1, 1.0)
            mw_ring.sample(1)
            killswitch_clean = (tl_bench.total_rows == n_tl
                                and am_bench.total_observed == n_am)
            memwatch_killswitch_clean = (mw_ring.total_samples
                                         == n_mw == 1)
            # numerics killswitch is STRUCTURAL (ISSUE 17): disabled,
            # the monitor must not even queue a sample...
            n_nm = nm_bench.total_samples + nm_bench.total_skipped
            nm_bench.observe(0, fake_on)
            numerics_monitor_clean = (
                nm_bench.total_samples + nm_bench.total_skipped == n_nm)
            # ops observatory (ISSUE 20), per-call gates: disabled, an
            # emit appends nothing and a ledger fold accounts nothing
            n_jr = jr_bench.seq
            jr_bench.emit("bench", "ghost")
            n_led = led_bench.account()["steps"]
            led_bench.on_step(led_row)
            ops_calls_clean = (jr_bench.seq == n_jr
                               and led_bench.account()["steps"]
                               == n_led)
            # ...and a session BUILT disabled must construct no
            # consumer / replay machinery and append zero extra step
            # outputs — the engine's build-time gate, checked on the
            # real output dict of a fresh mini-session
            sess2, *_ = parallax.parallel_run(
                simple.build_model(learning_rate=0.1),
                parallax_config=parallax.Config(
                    run_option="AR", search_partitions=False,
                    numerics_interval=1))
            try:
                out2 = sess2.run(None, feed_dict=batches[0])
                numerics_killswitch_clean = (
                    numerics_monitor_clean
                    and sess2.numerics is None
                    and sess2._numerics_last_batch is None
                    and "numerics" not in out2)
                # ISSUE 20 killswitch is STRUCTURAL too: a session
                # built disabled constructs NO journal ring, NO ledger
                # (no ops.* gauges) and NO alert engine/thread
                ops_killswitch_clean = (
                    ops_calls_clean
                    and sess2.journal is None
                    and sess2.ledger is None
                    and sess2.alerts is None
                    and sess2.ops_account() is None)
            finally:
                sess2.close()
        finally:
            obs.enable()

        # -- 3. informational raw A/B (interleaved, min-of-segments) ---
        def seg():
            t0 = time.perf_counter()
            r = None
            for i in range(seg_steps):
                r = sess.run("loss", feed_dict=batches[i % 8])
            float(r)
            return (time.perf_counter() - t0) / seg_steps

        on, off = [], []
        for s in range(2 * ab_segments):
            if s % 2 == 0:
                obs.enable()
                on.append(seg())
            else:
                obs.disable()
                off.append(seg())
        obs.enable()
        ab = min(on) / min(off) - 1.0

        collector.clear()  # don't leave bench spans in the ring
        return {
            "overhead_frac": round(overhead_frac, 5),
            "obs_us_per_step": round(obs_us, 2),
            "step_us": round(step_us, 1),
            "spans_per_step": round(spans_per_step, 2),
            "hist_records_per_step": round(hist_per_step, 2),
            "counter_incs_per_step": round(incs_per_step, 2),
            "timeline_rows_per_step": round(tl_rows_per_step, 2),
            "anomaly_obs_per_step": round(anom_per_step, 2),
            "numerics_samples_per_step": round(nm_samples_per_step, 3),
            "numerics_consumed_per_step": round(nm_consumed_per_step,
                                                3),
            "journal_emits_per_step": round(journal_per_step, 3),
            "alert_evals_per_step": round(evals_per_step, 6),
            "unit_costs_us": {"span": round(span_us, 3),
                              "histogram_record": round(hist_us, 3),
                              "counter_inc": round(inc_us, 3),
                              "batch_signature": round(sig_us, 3),
                              "timeline_row": round(tl_us, 3),
                              "anomaly_observe": round(anom_us, 3),
                              "memwatch_sample": round(mw_us, 3),
                              "profile_hook_idle": round(ph_us, 3),
                              "numerics_consume": round(nm_us, 3),
                              "numerics_skip": round(nm_skip_us, 3),
                              "journal_emit": round(journal_emit_us,
                                                    3),
                              "ledger_on_step": round(
                                  ledger_on_step_us, 3),
                              "alert_poll": round(alert_poll_us, 3),
                              "alert_eval": round(alert_eval_us, 3)},
            "killswitch_clean": killswitch_clean,
            "memwatch_killswitch_clean": memwatch_killswitch_clean,
            "numerics_killswitch_clean": numerics_killswitch_clean,
            "ops_killswitch_clean": ops_killswitch_clean,
            "ab_overhead_frac": round(ab, 4),
        }
    finally:
        from parallax_tpu import obs as _obs
        _obs.enable()
        sess.close()


def measure_serve(n_requests: int = 32, slots: int = 8, T: int = 12,
                  Ts: int = 6, model_dim: int = 32,
                  vocab: int = 64) -> dict:
    """The serving-path extension (ISSUE 12): the per-request trace —
    RequestRecord phase marks, the first-token decomposition snapshot,
    the ring publish and the serve.request span — must cost <= 2% of
    request service time, and the ``PARALLAX_OBS=0`` killswitch must
    collect NOTHING (no records created, no spans, no gauge samples).

    Same methodology as the training path: the layer is purely
    additive host-side code, so the enforced number is per-request
    instrument executions (counted from the records themselves —
    ``n_marks`` auto-adapts when phases are added) x micro-benched
    unit costs, over the measured mean request wall time; a raw A/B
    would be noise at this tolerance on shared CI."""
    from parallax_tpu import obs
    from parallax_tpu.obs import reqtrace, trace
    from parallax_tpu.obs.metrics import MetricsRegistry
    from tools import loadgen

    obs.enable()
    sess, make_feed = loadgen.demo_decode_session(
        slots=slots, T=T, Ts=Ts, model_dim=model_dim, vocab=vocab,
        speculative=False, prefill_chunk_layers=None)
    try:
        rep = loadgen.run_load(sess, make_feed, n_requests,
                               concurrency=slots)
        records = sess.request_records()
        if not records:
            raise RuntimeError("serve overhead rig collected no "
                               "request records")
        marks_per_req = sum(r["n_marks"] for r in records) \
            / len(records)
        walls = sorted(r["total_ms"] for r in records
                       if r["total_ms"])
        request_wall_us = (walls[len(walls) // 2]) * 1e3

        # unit costs on standalone instances (min over tight batches)
        bench_rec = reqtrace.RequestRecord(key=-1)
        phases = ["queue_wait", "prefill", "decode"]
        state = {"i": 0}

        def one_mark():
            bench_rec.mark(phases[state["i"] % 3])
            state["i"] += 1

        mark_us = _unit_cost_us(one_mark)
        ft_us = _unit_cost_us(lambda: bench_rec.first_token())
        ring = reqtrace.RequestTraceRing(MetricsRegistry(),
                                         capacity=64)
        done_rec = reqtrace.RequestRecord(key=-2)
        done_rec.complete()
        add_us = _unit_cost_us(lambda: ring.add(done_rec))

        def one_span():
            trace.record_span("obs-serve-bench", 0.0, 1e-3)

        span_us = _unit_cost_us(one_span)
        # per request: ctor+marks+completion-close (~marks+2 mark-
        # equivalents), one TTFT snapshot, one ring publish, one
        # serve.request span; per-request histogram records (ttft,
        # latency) ride the training-path budget already priced there
        obs_us = ((marks_per_req + 2) * mark_us + ft_us + add_us
                  + span_us)
        overhead_frac = obs_us / request_wall_us

        # killswitch: disabled, the request path must not collect —
        # no record object, no ring growth, no serve.request span
        collector = trace.get_collector()
        collector.clear()
        ring_before = sess.reqtrace.total
        obs.disable()
        try:
            r = sess.submit(make_feed(0))
            r.result(timeout=60.0)
        finally:
            obs.enable()
        ghost_spans = [e for e in collector.events()
                       if e.name == "serve.request"]
        killswitch_clean = (sess.reqtrace.total == ring_before
                            and not ghost_spans)
        collector.clear()
        return {
            "serve_overhead_frac": round(overhead_frac, 5),
            "serve_obs_us_per_request": round(obs_us, 2),
            "request_wall_us": round(request_wall_us, 1),
            "marks_per_request": round(marks_per_req, 2),
            "unit_costs_us": {"record_mark": round(mark_us, 3),
                              "first_token_snapshot": round(ft_us, 3),
                              "ring_add": round(add_us, 3),
                              "record_span": round(span_us, 3)},
            "requests": rep["completed"],
            "serve_killswitch_clean": killswitch_clean,
        }
    finally:
        from parallax_tpu import obs as _obs
        _obs.enable()
        sess.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--max-overhead", type=float, default=0.02,
                    help="fail when the decomposed overhead fraction "
                         "exceeds this (default 0.02 = 2%%)")
    ap.add_argument("--skip-serve", action="store_true",
                    help="skip the serving-path measurement")
    args = ap.parse_args(argv)
    result = measure(steps=args.steps, batch=args.batch)
    result["max_overhead"] = args.max_overhead
    result["ok"] = (result["overhead_frac"] <= args.max_overhead
                    and result["killswitch_clean"]
                    and result["memwatch_killswitch_clean"]
                    and result["numerics_killswitch_clean"]
                    and result["ops_killswitch_clean"])
    if not args.skip_serve:
        result["serve"] = measure_serve()
        result["ok"] = (result["ok"]
                        and result["serve"]["serve_overhead_frac"]
                        <= args.max_overhead
                        and result["serve"]["serve_killswitch_clean"])
    print(json.dumps(result, indent=2))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
