"""Convergence evidence — the only form of performance evidence the
reference itself publishes (BASELINE.md: lm1b_convergence.png /
resnet50_convergence.png / nmt_convergence.png figures, no numbers).

Trains the headline families at CPU-smoke scale through the SAME
engine paths the flagship uses (LM1B hybrid+slices; ResNet-50 on the
AR path with its real BatchNorm mutable state; NMT file-data
convergence is covered by the BLEU golden) and writes
perf/CONVERGENCE_r05.json: the loss/accuracy curves plus an
endpoint-drop + all-finite summary per curve (first-5 vs last-5 step
means — NOT a step-wise monotonicity claim). Not a throughput claim —
the committed artifact shows the training *math* converges end-to-end
through the engine features the bench exercises.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def lm1b_curve(steps=240):
    import numpy as np
    import parallax_tpu as parallax
    from parallax_tpu.models import lm1b

    cfg = lm1b.tiny_config(num_partitions=8, sparse_grad_mode="slices")
    sess, *_ = parallax.parallel_run(
        lm1b.build_model(cfg),
        parallax_config=parallax.Config(run_option="HYBRID",
                                        search_partitions=False,
                                        sparse_grad_mode="slices"))
    rng = np.random.default_rng(0)
    # a FIXED set of batches so the loss can actually go toward 0
    batches = [lm1b.make_batch(rng, 16, 8, cfg.vocab_size)
               for _ in range(4)]
    curve = []
    for i in range(steps):
        curve.append(float(sess.run("loss",
                                    feed_dict=batches[i % 4])))
    sess.close()
    return curve


def resnet_curve(steps=40):
    """ResNet-50 v1.5 at smoke shapes (32px) — a REAL BatchNorm model,
    so the engine's mutable model_state path is actually exercised
    (a LeNet stand-in here would silently skip it — r5 review)."""
    import numpy as np
    import parallax_tpu as parallax
    from parallax_tpu.models import cnn

    model = cnn.build_model("resnet50_v1.5", num_classes=10,
                            image_size=32, learning_rate=0.05)
    sess, *_ = parallax.parallel_run(
        model, parallax_config=parallax.Config(run_option="AR",
                                               search_partitions=False))
    rng = np.random.default_rng(0)
    batches = [cnn.make_batch(rng, 16, 32, 10) for _ in range(4)]
    curve = []
    for i in range(steps):
        loss, acc = sess.run(["loss", "accuracy"],
                             feed_dict=batches[i % 4])
        curve.append({"loss": float(loss), "accuracy": float(acc)})
    sess.close()
    return curve


def summarize(losses, head=5, tail=5):
    import math
    first = sum(losses[:head]) / head
    last = sum(losses[-tail:]) / tail
    return {"first_mean": round(first, 4), "last_mean": round(last, 4),
            "all_finite": bool(all(math.isfinite(x) for x in losses)),
            "decreased": bool(last < first),
            "drop_ratio": round(last / first, 4)}


def main():
    import jax

    result = {"platform": jax.devices()[0].platform,
              "note": ("CPU-smoke convergence curves through the full "
                       "engine paths; mirrors the reference's "
                       "convergence-figure evidence (BASELINE.md). NMT "
                       "convergence is evidenced separately by the "
                       "train->decode->BLEU~100 golden "
                       "(tests/test_nmt_data.py)")}
    lm = lm1b_curve()
    result["lm1b_hybrid_slices"] = {
        "loss_curve": [round(x, 4) for x in lm],
        **summarize(lm)}
    rc = resnet_curve()
    result["cnn_ar_batchnorm"] = {
        "curve": rc,
        **summarize([p["loss"] for p in rc]),
        "final_accuracy": rc[-1]["accuracy"]}
    out = os.path.join(os.path.dirname(__file__), "..", "perf",
                       "CONVERGENCE_r05.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    ok = all(result[k]["decreased"] and result[k]["all_finite"]
             for k in ("lm1b_hybrid_slices", "cnn_ar_batchnorm"))
    print(json.dumps({"lm1b_drop": result["lm1b_hybrid_slices"]
                      ["drop_ratio"],
                      "cnn_drop": result["cnn_ar_batchnorm"]
                      ["drop_ratio"],
                      "cnn_final_acc": rc[-1]["accuracy"],
                      "converged": ok}))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
