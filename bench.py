"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline (BASELINE.json): LM1B words/sec/chip. Trains the flagship LM1B
model (sampled softmax over the row-sharded 793k vocab) through
parallel_run and measures steady-state words/sec.

``vs_baseline`` compares against the naive dense path — full-softmax
LM1B, the "everything replicated, no sparse machinery" approach — at the
SAME (memory-limited) batch size, isolating the algorithmic win of the
sparse path from batch-size utilization. The headline value itself is
measured at the realistic batch size. Batch sizes scale with the chip
count (pure data parallelism).
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


def _run(model, cfg, batch_size, num_steps, steps, warmup, run_option,
         wire_stats=None):
    import parallax_tpu as parallax
    from parallax_tpu.models import lm1b

    sess, *_ = parallax.parallel_run(
        model, parallax_config=parallax.Config(run_option=run_option,
                                               search_partitions=False))
    try:
        rng = np.random.default_rng(0)
        batches = [lm1b.make_batch(rng, batch_size, num_steps,
                                   cfg.vocab_size) for _ in range(4)]
        for i in range(warmup):
            sess.run("loss", feed_dict=batches[i % 4])
        if wire_stats is not None:
            wire_stats.update(
                sess.engine.sparse_wire_bytes_per_step())
        jax.block_until_ready(sess.state.params)
        t0 = time.perf_counter()
        words = 0
        for i in range(steps):
            w = sess.run("words", feed_dict=batches[i % 4])
            words += w
        jax.block_until_ready(sess.state.params)
        dt = time.perf_counter() - t0
        return words / dt
    finally:
        # free HBM even on OOM so the retry loop's smaller attempt
        # starts clean
        sess.close()
        del sess


def main():
    from parallax_tpu.models import lm1b

    n_chips = jax.device_count()
    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:  # local smoke: tiny shapes
        cfg = lm1b.tiny_config(num_partitions=n_chips)
        bs, T, steps, warmup = 16 * n_chips, 8, 20, 3
        small_bs = 8 * n_chips
    else:
        cfg = lm1b.LM1BConfig(num_partitions=n_chips)
        bs, T, steps, warmup = 128 * n_chips, 20, 30, 5
        # full softmax materializes [B*T, 793k] logits; per-chip batch 16
        # is the largest that fits alongside params+opt state in HBM
        small_bs = 16 * n_chips

    # Headline: hybrid engine at the realistic batch size.
    wire = {}
    hybrid_wps = _run(lm1b.build_model(cfg), cfg, bs, T, steps, warmup,
                      "HYBRID", wire_stats=wire)
    # Baseline comparison at a common batch size both paths can run. The
    # full-softmax baseline materializes [B*T, V] logits; retry smaller
    # if it doesn't fit rather than losing the whole headline.
    vs_baseline = None
    try_bs = small_bs
    while vs_baseline is None and try_bs >= n_chips:
        try:
            # the OOM-prone full-softmax model goes first so a failed
            # size doesn't waste a measured sampled run
            full_small = _run(lm1b.build_full_softmax_model(cfg), cfg,
                              try_bs, T, max(5, steps // 3), warmup,
                              "HYBRID")
            sampled_small = _run(lm1b.build_model(cfg), cfg, try_bs, T,
                                 max(5, steps // 3), warmup, "HYBRID")
            vs_baseline = sampled_small / full_small
        except Exception as e:  # typically RESOURCE_EXHAUSTED
            print(f"# baseline at bs={try_bs} failed: "
                  f"{type(e).__name__}: {str(e)[:300]}", flush=True)
            try_bs //= 2
    # vs_baseline stays None (JSON null) if the baseline never ran —
    # never fabricate a parity number

    per_chip = hybrid_wps / n_chips
    result = {
        "metric": "lm1b_words_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "words/sec/chip",
        "vs_baseline": (round(vs_baseline, 3)
                        if vs_baseline is not None else None),
    }
    if wire.get("dense_allreduce_bytes"):
        # north-star secondary metric: sparse-grad bytes on wire per step
        # vs shipping dense [V, D] gradients
        result["sparse_grad_bytes_on_wire"] = wire["sparse_path_bytes"]
        result["dense_grad_bytes_equivalent"] = \
            wire["dense_allreduce_bytes"]
    print(json.dumps(result))


if __name__ == "__main__":
    main()
