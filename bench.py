"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline (BASELINE.json): LM1B words/sec/chip. Trains the flagship LM1B
model (sampled softmax over the row-sharded 793k vocab) through
parallel_run and measures steady-state words/sec.

``vs_baseline`` compares against the naive dense path — full-softmax
LM1B, the "everything replicated, no sparse machinery" approach — at the
SAME (memory-limited) batch size, isolating the algorithmic win of the
sparse path from batch-size utilization. The headline value itself is
measured at the realistic batch size. Batch sizes scale with the chip
count (pure data parallelism).

The process is split in two so a sick accelerator claim can't kill the
run before it prints anything: the parent (no jax import) probes backend
health in child processes with retry/backoff, then launches the actual
bench as a worker; if the accelerator never comes up it falls back to
CPU with the platform recorded in the JSON so a fallback number can
never masquerade as a TPU number.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


PROBE_LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_PROBE.log")

# Methodology version stamped into the JSON (VERDICT r4 weak item 4):
# cross-round vs_baseline comparisons are only valid within one version.
#   v1 (r1-r3): baseline = full-softmax at the HEADLINE batch size.
#   v2 (r4-r5): baseline = full-softmax at the largest COMMON batch both
#               paths fit (memory-limited), isolating the algorithmic win
#               from batch-size utilization; CPU smoke vocab 16k.
#   v3 (r6+):   headline methodology UNCHANGED from v2; the serve block
#               gains the continuous-decode concurrency sweep
#               (tokens/sec + TTFT per offered level, paged KV + chunked
#               prefill + speculative decode) and the decode block gains
#               the paged-vs-dense and speculative-vs-plain A/Bs
#               (ISSUE 6). The version bump exists so the regression
#               gate re-baselines the enlarged blocks; the same-build
#               A/B under v2 params attributes any headline move.
#               r7+: the serve block additionally carries a "fleet"
#               sub-block (chaos-harness failover/hot-swap latencies,
#               ISSUE 7) — a new sub-block, not a methodology change:
#               the regression gate SKIPS keys absent on either side,
#               so no version bump.
#               r8+: a top-level "ckpt" block (save/restore latency,
#               checkpoint bytes, async-save step-overhead A/B, train
#               chaos-harness outcome, ISSUE 9) — again a new block
#               with gate-side skip semantics, so no version bump.
#               r9+: a top-level "tune" block (auto-tuner v2 decision
#               record: plans enumerated/pruned/trialed, winner
#               predicted-vs-measured, search seconds, ISSUE 10) —
#               a new block with gate-side skip semantics, no bump.
#               r10+: the serve.continuous block gains trace-derived
#               keys (ttft_decomp phase shares, the per-percentile
#               dominant-cause report whose p99 keys are secondary-
#               gated, deadline_miss_budget_consumed) and serve.fleet
#               gains incident_correlated / ttft_decomp_max_rel_err
#               (ISSUE 12) — new keys, gate-side skip, no bump.
#               r14+: a top-level "lstm" block (ISSUE 14,
#               tools/bench_lstm.py: pallas-backward vs recompute-XLA
#               fwd+bwd A/B at op level and through one LM1B training
#               step, the interpret-tax witness, and the analytic
#               fwd+bwd HBM-bytes story at the flagship shape) — a
#               new block with gate-side skip semantics, no bump.
#               r16+: a top-level "attn" block (ISSUE 16,
#               tools/bench_paged_attn.py: fused paged-attention
#               kernel vs full-width einsum gather across pool
#               occupancies, the interpret-tax witness, and the
#               analytic live-pages-only vs gather HBM table at the
#               flagship decode shape) — a new block with gate-side
#               skip semantics, no bump.
#               r20+: a top-level "ops" block (ISSUE 20,
#               tools/check_goodput.py: run-lifetime goodput fraction
#               and badput breakdown from the chaos rig, plus the
#               journal-emit / alert-eval unit costs) — a new block
#               with gate-side skip semantics, no bump.
BENCH_VERSION = 3
BASELINE_BASIS = ("sampled-softmax vs full-softmax LM1B at the same "
                  "memory-limited batch; headline measured separately at "
                  "the realistic batch")


def _log_probe(attempt: int, status: str, stdout: str, stderr: str):
    """Append the FULL probe stdout/stderr to BENCH_PROBE.log — two
    rounds of TPU-capture failure left no record of why the backend
    never came up; the next diagnosis starts from this artifact."""
    try:
        with open(PROBE_LOG, "a") as f:
            f.write(f"=== probe attempt {attempt} at "
                    f"{time.strftime('%Y-%m-%d %H:%M:%S')} "
                    f"status={status} ===\n")
            f.write(f"env: JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS')}"
                    f" PALLAS_AXON_POOL_IPS="
                    f"{os.environ.get('PALLAS_AXON_POOL_IPS')}\n")
            if stdout:
                f.write("--- stdout ---\n" + stdout + "\n")
            f.write("--- stderr ---\n" + (stderr or "(empty)") + "\n\n")
    except OSError:
        pass


def _relay_addr() -> tuple:
    """(host, port) the axon client will actually dial. The client reads
    AXON_POOL_SVC_OVERRIDE (perf/probe_r05/h4_* probes: set ⇒ dialed,
    unset ⇒ pool-mode error), so a hardcoded 127.0.0.1:8083 here would
    misreport an overridden relay as down and skip a claim probe that
    could have succeeded (ADVICE r5). Accepts 'host', 'host:port' and
    '[v6addr]:port' forms; falls back to the loopback default."""
    override = os.environ.get("AXON_POOL_SVC_OVERRIDE", "").strip()
    host, port = "127.0.0.1", 8083
    if override:
        # tolerate URL-ish values: strip scheme and any path suffix so
        # an unparsed remainder can never leak ':' into the host (which
        # would flip _relay_listening to AF_INET6 on a non-v6 name)
        if "://" in override:
            override = override.split("://", 1)[1]
        override = override.split("/", 1)[0]
        host = override
        if override.startswith("["):           # [v6addr] or [v6addr]:port
            addr, _, rest = override[1:].partition("]")
            host = addr or host
            if rest.startswith(":") and rest[1:].isdigit():
                port = int(rest[1:])
        elif override.count(":") == 1:         # host:port (not bare v6)
            h, p = override.split(":")
            # empty host (":8084") falls back to loopback, never to the
            # unsplit override (which would leak ':' into the host)
            if p.isdigit():
                host, port = h or "127.0.0.1", int(p)
            else:
                host = h or "127.0.0.1"        # non-numeric port: drop it
    return host, port


def _relay_listening(timeout: float = 2.0) -> bool:
    """1-second claim-free readiness check. perf/probe_r05/POSTMORTEM.md:
    the axon client's device init is an HTTP GET against the relay's
    stateless port; when nothing listens there the init loop retries a
    synchronously-refused connect forever, so a refused TCP connect here
    means a jax.devices() probe can only burn its full timeout. No JAX,
    no claim state — safe to call any time."""
    import socket
    host, port = _relay_addr()
    s = socket.socket(socket.AF_INET6 if ":" in host else socket.AF_INET)
    s.settimeout(timeout)
    try:
        return s.connect_ex((host, port)) == 0
    except OSError:
        return False
    finally:
        s.close()


def _probe_backend(timeout: float, attempt: int = 0):
    """Try to initialize the default jax backend in a child process;
    returns (platform_or_empty, timed_out). The child runs with
    TPU/verbose logging on and its full output is persisted to
    BENCH_PROBE.log whatever happens."""
    env = dict(os.environ, TPU_MIN_LOG_LEVEL="0",
               TPU_STDERR_LOG_LEVEL="0")
    code = ("import jax; d = jax.devices()[0]; "
            "print(d.platform); print(getattr(d, 'device_kind', ''))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env,
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired as e:
        # a timeout here means the relay answered TCP but the init/claim
        # never completed; further probes would likely burn their full
        # timeout too, so the caller goes to the claim-free CPU path
        _log_probe(attempt, f"TIMEOUT after {timeout:.0f}s",
                   (e.stdout or b"").decode(errors="replace")
                   if isinstance(e.stdout, bytes) else (e.stdout or ""),
                   (e.stderr or b"").decode(errors="replace")
                   if isinstance(e.stderr, bytes) else (e.stderr or ""))
        return "", True
    _log_probe(attempt, f"rc={proc.returncode}", proc.stdout,
               proc.stderr)
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-1:]
        print(f"# backend probe failed: {' '.join(tail)[:200]} "
              f"(full log: BENCH_PROBE.log)", flush=True)
        return "", False
    out = proc.stdout.strip().splitlines()
    return (out[0] if out else ""), False


def _cpu_env(env):
    return dict(env, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
                XLA_FLAGS=(env.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8"
                           ).strip())


def main():
    """Orchestrator: probe (with backoff) -> run worker, streaming its
    output; every failure path still ends in a printed JSON line."""
    retries = int(os.environ.get("PARALLAX_BENCH_RETRIES", "3"))
    delay = float(os.environ.get("PARALLAX_BENCH_RETRY_SECS", "60"))
    worker_timeout = float(os.environ.get("PARALLAX_BENCH_TIMEOUT",
                                          "5400"))
    env = dict(os.environ, PARALLAX_BENCH_WORKER="1")
    platform = ""
    first_timeout = float(os.environ.get("PARALLAX_BENCH_PROBE_SECS",
                                         "900"))
    for attempt in range(retries):
        if not _relay_listening():
            # r5 post-mortem: refused relay port == the probe can only
            # hang to its timeout; don't burn 15 min discovering that
            relay = "%s:%d" % _relay_addr()
            _log_probe(attempt, f"RELAY DOWN ({relay} refused; "
                       "skipping jax.devices probe)", "", "")
            print(f"# axon relay not listening on {relay}; "
                  "skipping claim probe", flush=True)
        else:
            # long FIRST timeout: a cold relay handshake through the
            # tunnel can take minutes
            platform, timed_out = _probe_backend(
                timeout=first_timeout if attempt == 0 else 600,
                attempt=attempt)
            if platform:
                print(f"# backend up: {platform} (attempt {attempt + 1})",
                      flush=True)
                break
            if timed_out:
                print("# probe timed out; skipping further claim "
                      "attempts", flush=True)
                break
        if attempt < retries - 1:
            print(f"# retrying backend in {delay:.0f}s", flush=True)
            time.sleep(delay)
            delay = min(delay * 2, 600)
    if not platform:
        # accelerator unreachable: measure on CPU rather than report
        # nothing; the worker stamps the platform into the JSON
        print("# backend unavailable; falling back to CPU", flush=True)
        env = _cpu_env(env)

    # stream worker output live (a TPU bench runs for minutes; progress
    # lines matter); JSON still lands on stdout
    cmd = [sys.executable, os.path.abspath(__file__)]
    err = None
    try:
        rc = subprocess.run(cmd, env=env, timeout=worker_timeout
                            ).returncode
        if rc != 0:
            err = f"worker exited rc={rc}"
    except subprocess.TimeoutExpired:
        print("# worker timed out; rerunning on claim-free CPU",
              flush=True)
        try:
            rc = subprocess.run(cmd, env=_cpu_env(env),
                                timeout=worker_timeout).returncode
            err = None if rc == 0 else f"cpu rerun exited rc={rc}"
        except subprocess.TimeoutExpired:
            err = "worker and CPU rerun both timed out"
    if err is not None:
        # contract: EVERY failure path still prints one JSON line
        # (value 0 + error field can never masquerade as a result)
        print(json.dumps({
            "metric": "lm1b_words_per_sec_per_chip", "value": 0.0,
            "unit": "words/sec/chip", "vs_baseline": None,
            "error": err}))
        sys.exit(1)


def _run(model, cfg, batch_size, num_steps, steps, warmup, run_option,
         wire_stats=None, pipeline_stats=None, metrics_out=None,
         monitor_health=False, compile_out=None):
    import jax
    import numpy as np
    import parallax_tpu as parallax
    from parallax_tpu.models import lm1b

    sess, *_ = parallax.parallel_run(
        model, parallax_config=parallax.Config(
            run_option=run_option, search_partitions=False,
            sparse_grad_mode="slices",
            # compile-ahead engine (ISSUE 3): the batch size is its own
            # bucket (full batches pass through bit-identical — the
            # headline math is untouched) and sess.warmup() below
            # AOT-compiles it before the warmup steps, so compile
            # wall-time lands in compile_out instead of hiding inside
            # the first step
            shape_buckets=[batch_size],
            # health OFF on the timed runs: the in-graph grad-norm would
            # make the headline incomparable to rounds measured without
            # it — worker_main stamps health.* from a separate untimed
            # probe run instead
            monitor_health=monitor_health))
    try:
        rng = np.random.default_rng(0)
        batches = [lm1b.make_batch(rng, batch_size, num_steps,
                                   cfg.vocab_size) for _ in range(4)]
        sess.warmup(feed_dict=batches[0])
        for i in range(warmup):
            sess.run("loss", feed_dict=batches[i % 4])
        if wire_stats is not None:
            wire_stats.update(
                sess.engine.sparse_wire_bytes_per_step())
        jax.block_until_ready(sess.state.params)
        # Steady-state loop through the async pipeline: run_iter preps +
        # places batch t+1 on a background thread while step t runs. The
        # per-step "loss" fetch is LAZY (a Fetch handle — no host<->
        # device round trip, so dispatch never serializes; the old loop
        # had to fetch [] to get the same property); only the last one
        # is materialized, which records the real pipeline-drain time as
        # blocked_on_device. One long window: splitting into best-of-k
        # windows was tried (r5) and REJECTED — the per-window pipeline
        # drain cost more than host-interference noise on every backend.
        # The words count equals the feed's weight sum — the same value
        # the "words" metric computes on device.
        words_per_batch = [float(b["w"].sum()) for b in batches]
        t0 = time.perf_counter()
        words = 0.0
        last = None
        feed = (batches[i % 4] for i in range(steps))
        for i, last in enumerate(sess.run_iter(feed, fetches="loss")):
            words += words_per_batch[i % 4]
        float(last)  # drain: blocks until the final step retires
        jax.block_until_ready(sess.state.params)
        dt = time.perf_counter() - t0
        if pipeline_stats is not None:
            # dispatch-gap / H2D-bytes / blocked-on-device over the
            # measured window (the overlap observability this bench
            # guards; regressions show up as a growing dispatch gap)
            pipeline_stats.update(sess.pipeline_stats.summary())
        if metrics_out is not None:
            # the full metrics-registry snapshot (ISSUE 2): pipeline.*,
            # engine recompiles, health.* (grad norm / loss finiteness),
            # device memory gauges where the backend reports them
            metrics_out.update(sess.metrics_snapshot())
        if compile_out is not None:
            # compile-ahead report (ISSUE 3): bucket signatures,
            # per-bucket AOT compile seconds, executable-/engine-cache
            # hit/miss counts over the measured run
            compile_out.update(sess.compile_stats())
        return words / dt
    finally:
        # free HBM even on OOM so the retry loop's smaller attempt
        # starts clean
        sess.close()
        del sess


def _load_prev_round(root=None):
    """The previous round's bench block: the highest-numbered
    BENCH_r*.json in the repo root, unwrapped from the driver format
    (shared conventions: tools/bench_artifacts.py); None when
    absent/unreadable."""
    here = os.path.dirname(os.path.abspath(__file__))
    root = root or here
    # the helpers live next to THIS file, whatever root is scanned
    sys.path.insert(0, os.path.join(here, "tools"))
    try:
        from bench_artifacts import load_block, round_paths
    except ImportError:
        return None
    paths = round_paths(root)
    return load_block(paths[-1]) if paths else None


def _needs_harness_ab(prev) -> bool:
    """True when this round must record the same-round A/B (VERDICT r5
    item 6): the previous round exists, ran under a DIFFERENT
    bench_version, and left the harness parameters to replay. The A/B
    re-measures the CURRENT build under the previous round's harness
    parameters, so a cross-round delta decomposes into 'methodology
    moved' vs 'the build moved' in-artifact."""
    return (isinstance(prev, dict)
            and prev.get("bench_version") is not None
            and prev.get("bench_version") != BENCH_VERSION
            and isinstance(prev.get("harness"), dict))


def _harness_hash() -> str:
    """sha256 of this file's bytes: two rounds with equal hashes ran
    the IDENTICAL harness, so a headline delta is the build's."""
    import hashlib
    try:
        with open(os.path.abspath(__file__), "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return "unknown"


def worker_main():
    import jax

    from parallax_tpu.models import lm1b

    n_chips = jax.device_count()
    platform = jax.devices()[0].platform
    on_cpu = platform == "cpu"
    if on_cpu:  # local smoke: tiny shapes
        # fp32 compute on CPU: host XLA emulates bf16 matmuls by
        # widening per-op, which is what regressed the r3 fallback
        # number (VERDICT r3 weak item 1) — the bf16 casts are a
        # TPU-MXU optimization with no CPU analogue
        import jax.numpy as jnp
        # the vocab must be big enough for the sampled-vs-full
        # comparison to measure the algorithm, not the harness: at the
        # old vocab=1000 the "dense baseline" was a trivial [N, 1000]
        # matmul and vs_baseline read backwards (r2/r3)
        cfg = lm1b.tiny_config(num_partitions=n_chips,
                               sparse_grad_mode="slices",
                               compute_dtype=jnp.float32,
                               vocab_size=16000, num_samples=128)
        bs, T, steps, warmup = 16 * n_chips, 8, 20, 3
        small_bs = 8 * n_chips
    else:
        bs, T, steps, warmup = 128 * n_chips, 20, 30, 5
        # slices mode: table grads stay (ids, rows) pairs end-to-end —
        # the reference's IndexedSlices processing and the fast path on
        # TPU (no dense [V, D] cotangent / accumulator pass per step).
        # lstm_impl='pallas': the r5 hoisted-input/resident-recurrent
        # kernel serves the flagship (ROADMAP item 17) — default on TPU.
        cfg = lm1b.LM1BConfig(num_partitions=n_chips,
                              sparse_grad_mode="slices",
                              lstm_impl="pallas")
        # full softmax materializes [B*T, 793k] logits; per-chip batch 16
        # is the largest that fits alongside params+opt state in HBM
        small_bs = 16 * n_chips

    # Headline: hybrid engine at the realistic batch size.
    wire = {}
    pipe = {}
    metrics_snap = {}
    compile_snap = {}
    hybrid_wps = _run(lm1b.build_model(cfg), cfg, bs, T, steps, warmup,
                      "HYBRID", wire_stats=wire, pipeline_stats=pipe,
                      metrics_out=metrics_snap, compile_out=compile_snap)
    # Baseline comparison at a common batch size both paths can run. The
    # full-softmax baseline materializes [B*T, V] logits; retry smaller
    # if it doesn't fit rather than losing the whole headline.
    vs_baseline = None
    try_bs = small_bs
    # r5: the comparison pair runs at least 12 steps each — at the old
    # max(5, steps//3) the short full-softmax window made vs_baseline
    # swing ±15% run-to-run on CPU (r4 7.9 vs r5 probes 6.1-6.9)
    cmp_steps = max(12, steps // 2)
    while vs_baseline is None and try_bs >= n_chips:
        try:
            # the OOM-prone full-softmax model goes first so a failed
            # size doesn't waste a measured sampled run
            full_small = _run(lm1b.build_full_softmax_model(cfg), cfg,
                              try_bs, T, cmp_steps, warmup, "HYBRID")
            sampled_small = _run(lm1b.build_model(cfg), cfg, try_bs, T,
                                 cmp_steps, warmup, "HYBRID")
            vs_baseline = sampled_small / full_small
        except Exception as e:  # typically RESOURCE_EXHAUSTED
            print(f"# baseline at bs={try_bs} failed: "
                  f"{type(e).__name__}: {str(e)[:300]}", flush=True)
            try_bs //= 2
    # vs_baseline stays None (JSON null) if the baseline never ran —
    # never fabricate a parity number

    # Health probe (untimed): grad-norm / loss-finite flow through the
    # registry on a short run with monitor_health=True; merged into the
    # stamped snapshot so the BENCH JSON carries them without the
    # in-graph norm compute touching any timed window. Costs one extra
    # engine compile — PARALLAX_BENCH_HEALTH=0 skips it when that
    # matters more than the health keys (e.g. a quick TPU spot-check).
    if os.environ.get("PARALLAX_BENCH_HEALTH", "1") != "0":
        try:
            health_snap = {}
            _run(lm1b.build_model(cfg), cfg, small_bs, T, 6, 2, "HYBRID",
                 metrics_out=health_snap, monitor_health=True)
            metrics_snap.update({k: v for k, v in health_snap.items()
                                 if k.startswith("health.")})
        except Exception as e:
            print(f"# health probe failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    # Serve section (ISSUE 4): the serving subsystem's own headline —
    # a short mixed-length closed-loop load through ServeSession
    # (tools/loadgen.py), stamped so request-path latency/QPS get a
    # per-round trajectory next to the training headline. Untimed wrt
    # the training windows (runs after them); PARALLAX_BENCH_SERVE=0
    # skips it.
    serve_snap = None
    if os.environ.get("PARALLAX_BENCH_SERVE", "1") != "0":
        try:
            sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            from tools import loadgen
            ssess, make_feed = loadgen.demo_session(
                max_batch=8, length_buckets=(16, 32), dim=128, layers=2)
            try:
                load = loadgen.run_load(ssess, make_feed, 48,
                                        concurrency=4)
                stats = ssess.stats()
            finally:
                ssess.close()
            occ = stats.get("serve.batch_occupancy") or {}
            step = stats.get("serve.step_ms") or {}
            serve_snap = {
                "requests": load["submitted"],
                "completed": load["completed"],
                "qps": load["qps"],
                "latency_ms": load["latency_ms"],
                "recompiles": stats.get("serve.recompiles", 0),
                "batch_occupancy_mean": round(occ.get("mean", 0), 3)
                if occ else None,
                "step_ms_p50": round(step.get("p50", 0), 3)
                if step else None,
            }
            # Continuous-decode concurrency sweep (ISSUE 6): paged KV +
            # chunked prefill + speculative decode at 1x..8x the r4/r5
            # serve concurrency (max_batch was 8) — tokens/sec and TTFT
            # per offered level, the 8x-64x-concurrency claim as one
            # artifact. PARALLAX_BENCH_SWEEP=0 skips just the sweep.
            if os.environ.get("PARALLAX_BENCH_SWEEP", "1") != "0":
                levels = (8, 16, 32, 64)
                # paged pool, one-dispatch prefill, no speculation:
                # the sweep prices CONCURRENCY (the paged pool's win);
                # chunked prefill trades refill throughput for bounded
                # step stall and speculative economics depend on draft
                # quality — both are priced separately (the SLO guard's
                # decode phase and the decode block's A/Bs)
                rows = loadgen.sweep_decode(
                    levels=levels, speculative=False,
                    prefill_chunk_layers=None, T=32)
                by_level = {r["offered_concurrency"]: r for r in rows}
                # the *_at_8x keys are regression-gated by name
                # (tools/check_regression.py SECONDARY_GATES), so they
                # bind to the literal 8x-of-r4 level (8 * 8 = 64) —
                # absent from a future sweep, they stamp None and the
                # gate SKIPS instead of silently comparing a different
                # concurrency
                at8 = by_level.get(8 * 8)
                best = max((r["tokens_per_sec"] or 0) for r in rows)
                serve_snap["continuous"] = {
                    "sweep": rows,
                    "prev_round_max_concurrency": 8,
                    "max_offered_concurrency": max(levels),
                    "concurrency_multiple": max(levels) // 8,
                    "tokens_per_sec_best": best or None,
                    "ttft_ms_p50_at_8x": ((at8.get("ttft_ms") or {})
                                          .get("p50") if at8 else None),
                    "tokens_per_sec_at_8x": (at8.get("tokens_per_sec")
                                             if at8 else None),
                    "recompiles": sum(r.get("recompiles", 0)
                                      for r in rows),
                    # trace-derived keys (ISSUE 12, obs/reqtrace +
                    # tools/serve_report): per-phase TTFT shares and
                    # the per-percentile dominant-cause report at the
                    # 8x level — report.buckets.p99.* is secondary-
                    # gated by name (tools/check_regression.py)
                    "ttft_decomp": (at8.get("ttft_decomp")
                                    if at8 else None),
                    "deadline_miss_budget_consumed": (
                        at8.get("deadline_miss_budget_consumed")
                        if at8 else None),
                    "report": (at8.get("attribution")
                               if at8 else None),
                }
            # Fleet robustness block (ISSUE 7): the chaos harness run
            # end to end — injected replica crash with failover and a
            # mid-traffic weight hot-swap over a 2-replica decode
            # fleet; failover recovery latency and hot-swap blackout
            # window tracked per round (secondary-gated by
            # tools/check_regression.py). PARALLAX_BENCH_FLEET=0 skips.
            if os.environ.get("PARALLAX_BENCH_FLEET", "1") != "0":
                from tools import check_fleet_faults
                fres = check_fleet_faults.measure()
                fviol = check_fleet_faults.check(fres)
                serve_snap["fleet"] = dict(
                    fres["bench"],
                    ok=not fviol,
                    violations=fviol[:3] or None)
            # Prefix-reuse block (ISSUE 15): the radix-cache guard run
            # end to end at 50% shared-prefix load — warm-vs-cold TTFT
            # p50, tokens/sec with sharing on, hit rate, evictions and
            # the exact-reuse/leak/isolation verdicts, per round.
            # serve.prefix.ttft_ms_p50_warm and .hit_rate are
            # secondary-gated (tools/check_regression.py); no
            # BENCH_VERSION bump (additive block, gates skip when
            # absent). PARALLAX_BENCH_PREFIX=0 skips.
            if os.environ.get("PARALLAX_BENCH_PREFIX", "1") != "0":
                from tools import check_prefix_reuse
                pres = check_prefix_reuse.measure(
                    n_requests=30, prefix_share=0.5)
                pviol = check_prefix_reuse.check(pres)
                serve_snap["prefix"] = {
                    "prefix_share": pres["prefix_share"],
                    "ttft_ms_p50_warm": pres["ttft_ms_p50_warm"],
                    "ttft_ms_p50_cold": pres[
                        "ttft_ms_p50_cold_nosharing"],
                    "tokens_per_sec_warm": pres["tokens_per_sec_warm"],
                    "tokens_per_sec_nosharing": pres[
                        "tokens_per_sec_nosharing"],
                    "hit_rate": pres["hit_rate"],
                    "full_hits": pres["full_hits"],
                    "cow_copies": pres["cow_copies"],
                    "evictions": pres["evictions"],
                    "token_mismatches": pres["token_mismatches"],
                    "tenant_isolation_clean": pres[
                        "tenant_isolation"].get("b_hits_delta") == 0,
                    "ok": not pviol,
                    "violations": pviol[:3] or None,
                }
            # Disaggregation A/B block (ISSUE 19): colocated ServeFleet
            # vs DisaggFleet (prefill pool -> wire transfer -> decode
            # pool) replaying the SAME mixed-regime request stream —
            # long-prefill/short-decode mixed with short-prefill/long-
            # decode, the traffic shape that pulls a colocated replica
            # in opposite directions. serve.disagg.ttft_ms_p99 and
            # serve.disagg.tokens_per_sec are secondary-gated
            # (tools/check_regression.py); no BENCH_VERSION bump
            # (additive block, gates skip when absent).
            # PARALLAX_BENCH_DISAGG=0 skips.
            if os.environ.get("PARALLAX_BENCH_DISAGG", "1") != "0":
                from parallax_tpu.serve import (DisaggFleet,
                                                FleetConfig,
                                                ServeFleet)
                mk = loadgen.demo_disagg_rig(slots=4)
                dfeed, dmnt = loadgen.mixed_regime_feed(vocab=64)
                n_req = 24

                colo = ServeFleet(mk, config=FleetConfig(
                    num_replicas=2, min_replicas=1))
                try:
                    # unmeasured warmup drains: first-touch lazy init
                    # on each arm's serving path would otherwise land
                    # a ~1s bimodal spike in the gated p99
                    for i in range(2):
                        colo.submit(dfeed(i), max_new_tokens=dmnt(i)
                                    ).result(timeout=120)
                    crep = loadgen.run_load(
                        colo, dfeed, n_requests=n_req, concurrency=4,
                        max_new_tokens=dmnt)
                finally:
                    colo.close()

                dis = DisaggFleet(
                    mk, mk,
                    prefill_config=FleetConfig(num_replicas=1,
                                               min_replicas=1),
                    decode_config=FleetConfig(num_replicas=1,
                                              min_replicas=1))
                try:
                    for i in range(2):
                        dis.submit(dfeed(i), max_new_tokens=dmnt(i)
                                   ).result(timeout=120)
                    drep = loadgen.run_load(
                        dis, dfeed, n_requests=n_req, concurrency=4,
                        max_new_tokens=dmnt)
                    dsnap = dis.metrics.snapshot()
                    drecomp = dis.recompiles()
                finally:
                    dis.close()

                def _arm(rep):
                    return {
                        "completed": rep["completed"],
                        "tokens_per_sec": rep["tokens_per_sec"],
                        "ttft_ms_p50": rep["ttft_ms"]["p50"],
                        "ttft_ms_p99": rep["ttft_ms"]["p99"],
                    }

                tms = dsnap.get("serve.disagg.transfer_ms") or {}
                pms = dsnap.get("serve.disagg.prefill_ms") or {}
                serve_snap["disagg"] = {
                    "colocated": _arm(crep),
                    "disaggregated": _arm(drep),
                    # gate-addressable copies of the disaggregated
                    # arm: serve.disagg.ttft_ms_p99 and
                    # serve.disagg.tokens_per_sec resolve here
                    "ttft_ms_p99": drep["ttft_ms"]["p99"],
                    "tokens_per_sec": drep["tokens_per_sec"],
                    "transfers": dsnap.get("serve.disagg.transfers"),
                    "transfer_bytes": dsnap.get(
                        "serve.disagg.transfer_bytes"),
                    "transfer_ms_p50": tms.get("p50"),
                    "transfer_ms_mean": tms.get("mean"),
                    "prefill_ms_p50": pms.get("p50"),
                    "prefill_fallbacks": dsnap.get(
                        "serve.disagg.prefill_fallbacks"),
                    "recompiles": drecomp,
                    # the caveat lives IN the artifact so a reader of
                    # bench.json sees it without the docs
                    "note": ("single-process CPU arms: the 'wire' is "
                             "a host memcpy and both pools share one "
                             "machine, so the colocated-vs-disagg "
                             "verdict does not transfer to TPUs; "
                             "cross-round drift of the gated keys is "
                             "the signal, not the A/B winner"),
                }
        except Exception as e:
            print(f"# serve bench failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    # Decode block (VERDICT r5 satellite + ISSUE 6): cached-vs-
    # cacheless NMT decode ratios plus the paged-vs-dense and
    # speculative-vs-plain A/Bs (tools/nmt_decode_timing.py) — every
    # serve-side latency primitive tracked per round instead of a
    # one-off perf file. PARALLAX_BENCH_DECODE=0 skips it.
    decode_snap = None
    if os.environ.get("PARALLAX_BENCH_DECODE", "1") != "0":
        try:
            from tools import nmt_decode_timing
            d = nmt_decode_timing.measure(lengths=(32, 64), batch=4,
                                          repeats=2)
            decode_snap = {
                "rows": d["rows"],
                "ratio_grows_with_T": d["ratio_grows_with_T"],
                "paged_vs_dense": d.get("paged_vs_dense"),
                "spec_vs_plain": d.get("spec_vs_plain"),
                "spec_ceiling": d.get("spec_ceiling"),
            }
        except Exception as e:
            print(f"# decode bench failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    # LSTM backward block (ISSUE 14): the flagship recurrence's
    # fwd+bwd A/B — pallas backward kernel vs the recompute-XLA VJP —
    # at op level and through one real LM1B training step, plus the
    # analytic fwd+bwd HBM-bytes story at the true flagship shape.
    # Off-TPU the pallas programs run interpreted, so the measured
    # ratios carry the interpret-tax witness and the CPU-relative
    # caveat in-artifact; tools/check_regression.py secondary-gates
    # lstm.op_ms.pallas_bwd and (drift) lstm.pallas_over_recompute.
    # PARALLAX_BENCH_LSTM=0 skips.
    lstm_snap = None
    if os.environ.get("PARALLAX_BENCH_LSTM", "1") != "0":
        try:
            sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            from tools import bench_lstm
            lstm_snap = bench_lstm.measure()
        except Exception as e:
            print(f"# lstm bench failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    # Paged-attention block (ISSUE 16): one paged decode-step
    # attention A/B — fused Pallas kernel (live pages only) vs the
    # full-width einsum gather — across pool occupancies, plus the
    # analytic allocated-pages-only vs full-width HBM table at the
    # flagship decode shape. Off-TPU the kernel runs interpreted, so
    # the measured ratios carry the interpret-tax witness (the
    # equal-bytes 100%-occupancy ratio) and the CPU-relative caveat
    # in-artifact; tools/check_regression.py secondary-gates
    # attn.step_ms.kernel and (drift) attn.kernel_over_einsum.
    # PARALLAX_BENCH_ATTN=0 skips.
    attn_snap = None
    if os.environ.get("PARALLAX_BENCH_ATTN", "1") != "0":
        try:
            sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            from tools import bench_paged_attn
            attn_snap = bench_paged_attn.measure()
        except Exception as e:
            print(f"# attn bench failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    # Auto-tuner block (ISSUE 10): one MeshSearch decision end to end
    # on the smoke-scale flagship — candidates enumerated / pruned /
    # trialed, predicted-vs-measured ms for the measured winner,
    # search wall seconds and the engine-cache counters that prove
    # trials reuse compiles. tools/check_regression.py secondary-gates
    # tune.search_seconds and (two-sided) tune.predicted_over_measured
    # drift. Runs in a SUBPROCESS (tools/bench_tune.py): a multi-mesh
    # search in-process is the known XLA:CPU hard-crash workload, and
    # an abort must cost this round its tune block, not the whole
    # artifact. The child pins itself to CPU (on a TPU round the
    # worker holds the chip claim; the block stamps its platform), so
    # the ratio is CPU-relative — cross-round DRIFT is the gated
    # signal, never the absolute value. PARALLAX_BENCH_TUNE=0 skips.
    tune_snap = None
    if os.environ.get("PARALLAX_BENCH_TUNE", "1") != "0":
        try:
            here = os.path.dirname(os.path.abspath(__file__))
            proc = subprocess.run(
                [sys.executable, os.path.join(here, "tools",
                                              "bench_tune.py")],
                env=dict(os.environ), capture_output=True, text=True,
                timeout=600)
            start = proc.stdout.find("{")
            if proc.returncode == 0 and start >= 0:
                tune_snap = json.loads(proc.stdout[start:])
            else:
                print(f"# tune bench child failed rc="
                      f"{proc.returncode}: "
                      f"{(proc.stderr or '')[-200:]}", flush=True)
        except Exception as e:
            print(f"# tune bench failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    # Plan-observatory block (ISSUE 13): one profiled window end to
    # end on the embedding rig — measured per-op attribution shares,
    # coverage vs the device step wall with the residual explicit,
    # and the per-term calibration ratios (predicted/measured for the
    # on-chip and wire roofline terms). tools/check_regression.py
    # secondary-gates profile.attribution_coverage and (two-sided)
    # the wire calibration drift — the ratio is CPU-relative off-TPU,
    # so cross-round DRIFT is the gated signal, never the absolute.
    # Subprocess child (tools/check_profile_attrib.py — the same
    # tier-1 guard): jax.profiler capture is process-global state an
    # abort must not leak into the headline. PARALLAX_BENCH_PROFILE=0
    # skips. No BENCH_VERSION bump: new block, gate-side skip.
    profile_snap = None
    if os.environ.get("PARALLAX_BENCH_PROFILE", "1") != "0":
        try:
            here = os.path.dirname(os.path.abspath(__file__))
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(here, "tools",
                              "check_profile_attrib.py")],
                env=dict(os.environ), capture_output=True, text=True,
                timeout=600)
            start = proc.stdout.find("{")
            if start >= 0:
                profile_snap = json.loads(proc.stdout[start:])
                if proc.returncode != 0:
                    print(f"# profile guard violations: "
                          f"{profile_snap.get('violations')}",
                          flush=True)
            else:
                print(f"# profile bench child failed rc="
                      f"{proc.returncode}: "
                      f"{(proc.stderr or '')[-200:]}", flush=True)
        except Exception as e:
            print(f"# profile bench failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    # Checkpoint cost block (ISSUE 9): save/restore latency, bytes,
    # and the async-save step-overhead A/B (async critical-path cost
    # vs the synchronous path, amortized over the save cadence —
    # tools/bench_ckpt.py, budget <= 2%). The chaos-harness outcome
    # (tools/check_train_faults.py) rides along so every round proves
    # SIGKILL-exact-resume / torn-fallback / NaN-rollback still hold.
    # PARALLAX_BENCH_CKPT=0 skips; check_regression secondary-gates
    # ckpt.save_ms / ckpt.restore_ms between compatible rounds.
    ckpt_snap = None
    if os.environ.get("PARALLAX_BENCH_CKPT", "1") != "0":
        try:
            sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            from tools import bench_ckpt
            ckpt_snap = bench_ckpt.measure()
            if os.environ.get("PARALLAX_BENCH_CKPT_FAULTS", "1") != "0":
                from tools import check_train_faults
                cres = check_train_faults.measure()
                cviol = check_train_faults.check(cres)
                ckpt_snap["faults"] = dict(
                    cres["bench"], ok=not cviol,
                    violations=cviol[:3] or None)
        except Exception as e:
            print(f"# ckpt bench failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    # Numerics observatory block (ISSUE 17): per-layer stats trail
    # analysis on the sampled simple-model rig (which layer, which
    # risk), both kernel-drift sentinels clean AND with an injected
    # perturbation (clean must stay silent, perturbed must flag), and
    # the host-side per-sample consume cost. tools/check_regression.py
    # secondary-gates the sentinels' accuracy (two-sided drift: the
    # agreement is CPU-relative under Pallas interpret mode, so
    # cross-round DRIFT is the signal) and numerics.consume_us.
    # PARALLAX_BENCH_NUMERICS=0 skips. No BENCH_VERSION bump: new
    # block, gate-side skip.
    numerics_snap = None
    if os.environ.get("PARALLAX_BENCH_NUMERICS", "1") != "0":
        try:
            sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            from tools import numerics_report
            numerics_snap = numerics_report.measure()
        except Exception as e:
            print(f"# numerics bench failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    # Ops observatory block (ISSUE 20): the run-lifetime goodput
    # fraction and badput breakdown from the chaos rig
    # (tools/check_goodput.py: clean / SIGKILL-resume / NaN-rollback
    # children, each account summing to wall by construction), plus
    # the journal-emit and alert-eval unit costs priced standalone.
    # tools/check_regression.py secondary-gates ops.goodput_fraction
    # (a falling fraction means the instrumented loop is losing wall
    # to badput) and ops.alert_eval_us (a full rule pass creeping up).
    # Absolutes are CPU-relative. PARALLAX_BENCH_OPS=0 skips. No
    # BENCH_VERSION bump: new block, gate-side skip.
    ops_snap = None
    if os.environ.get("PARALLAX_BENCH_OPS", "1") != "0":
        try:
            sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            from parallax_tpu import obs
            from tools import check_goodput
            from tools.check_obs_overhead import _unit_cost_us
            gres = check_goodput.measure()
            gviol = check_goodput.check(gres)
            jr = obs.EventJournal(capacity=64,
                                  registry=obs.MetricsRegistry())
            eng = obs.AlertEngine(obs.MetricsRegistry(),
                                  rules=obs.builtin_rules(),
                                  interval_s=3600.0)
            ops_snap = dict(
                gres["bench"],
                goodput_fraction=gres["bench"]
                ["clean_goodput_fraction"],
                journal_emit_us=round(_unit_cost_us(
                    lambda: jr.emit("bench", "tick", n=1)), 3),
                alert_eval_us=round(_unit_cost_us(
                    eng.evaluate, iters=200, batches=5), 3),
                chaos_ok=not gviol,
                violations=gviol[:3] or None)
        except Exception as e:
            print(f"# ops bench failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    per_chip = hybrid_wps / n_chips

    # Same-round A/B on a bench_version bump (VERDICT r5 item 6): the
    # CURRENT build re-measured under the PREVIOUS round's harness
    # parameters. The pair (value, value_under_prev_params) separates
    # "the methodology moved the number" from "the build moved the
    # number" — the r4→r5 −23% had neither. PARALLAX_BENCH_AB=0 skips.
    ab_snap = None
    prev = _load_prev_round()
    if (_needs_harness_ab(prev)
            and os.environ.get("PARALLAX_BENCH_AB", "1") != "0"):
        try:
            ph = prev["harness"]
            ab_wps = _run(lm1b.build_model(cfg), cfg,
                          int(ph.get("batch_size", bs)),
                          int(ph.get("seq_len", T)),
                          int(ph.get("steps_measured", steps)),
                          int(ph.get("warmup_steps", warmup)), "HYBRID")
            ab_per_chip = ab_wps / n_chips
            ab_snap = {
                "prev_bench_version": prev.get("bench_version"),
                "prev_value": prev.get("value"),
                "prev_harness_sha256": ph.get("bench_sha256"),
                "prev_vocab_size": ph.get("vocab_size"),
                "value_under_prev_params": round(ab_per_chip, 1),
                "value_current_params": round(per_chip, 1),
                "current_over_prev_params": round(
                    per_chip / ab_per_chip, 3) if ab_per_chip else None,
                "note": ("same build, previous round's harness params "
                         "(batch/seq/steps/warmup; vocab stays "
                         "current) — attributes methodology vs build "
                         "moves"),
            }
        except Exception as e:
            print(f"# harness A/B failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)
    # MFU: analytic matmul FLOPs per word (fwd+bwd) over the chip's
    # published bf16 peak — the judged utilization number (VERDICT r2
    # item 2). Null on CPU / unknown hardware, never fabricated.
    from parallax_tpu.common import flops as flops_lib
    fpw = flops_lib.lm1b_matmul_flops_per_word(cfg)
    # the env gen hint (PALLAS_AXON_TPU_GEN) describes the tunnel's TPU,
    # not whatever backend this run actually landed on — consulting it
    # on a non-TPU fallback produced the misleading "mfu": 0.0 of r3;
    # device_peak_flops owns that platform gate (VERDICT r5 item 5:
    # mfu is non-null the moment platform=="tpu" and the kind/hint
    # matches the table — tested under a TPU stub in test_forensics)
    peak = flops_lib.device_peak_flops(
        platform, getattr(jax.devices()[0], "device_kind", ""),
        os.environ.get("PALLAS_AXON_TPU_GEN"))
    mfu = flops_lib.mfu(fpw, per_chip, peak)
    result = {
        "metric": "lm1b_words_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "words/sec/chip",
        "vs_baseline": (round(vs_baseline, 3)
                        if vs_baseline is not None else None),
        "bench_version": BENCH_VERSION,
        "baseline_basis": BASELINE_BASIS,
        "platform": platform,
        "n_chips": n_chips,
        "flops_per_word": fpw,
        "flops_per_step": fpw * bs * T,
        "device_peak_flops": peak,
        "mfu": round(mfu, 4) if mfu is not None else None,
        # async-pipeline health over the headline window. Kept ALONGSIDE
        # the registry snapshot below (which carries the same pipeline.*
        # data in histogram form) for cross-round continuity: BENCH_r0x
        # consumers read this key; drop it once comparisons re-baseline.
        "pipeline": pipe or None,
        # metrics-registry snapshot over the headline window (obs/):
        # pipeline.* overlap signals, steps/sec, engine recompiles,
        # health grad-norm / loss-finite (untimed probe run), device
        # memory when the backend reports it
        "metrics": metrics_snap or None,
        # compile-ahead engine over the headline run (ISSUE 3): bucket
        # signatures, per-bucket AOT warmup compile seconds, and the
        # executable-/engine-cache hit/miss counts — a healthy run
        # shows zero executable misses and engine.recompiles == 0 in
        # the metrics snapshot above
        "compile": compile_snap or None,
        # online serving (ISSUE 4): ServeSession QPS/latency under the
        # loadgen mixed-length closed loop, recompiles (healthy: 0)
        "serve": serve_snap,
        # KV-cached vs cache-less decode ratios (the serve-side latency
        # primitive), tracked per round
        "decode": decode_snap,
        # pallas LSTM backward A/B (ISSUE 14): kernel vs recompute-XLA
        # fwd+bwd step_ms (CPU-relative off-TPU, interpret-tax witness
        # stamped) + the analytic flagship HBM-bytes story
        "lstm": lstm_snap,
        # paged-attention decode A/B (ISSUE 16): fused Pallas kernel
        # vs full-width einsum gather across pool occupancies
        # (CPU-relative off-TPU, interpret-tax witness stamped) + the
        # analytic live-pages-only vs gather HBM table at the
        # flagship decode shape
        "attn": attn_snap,
        # checkpoint/recovery costs (ISSUE 9): save/restore latency,
        # bytes, async-vs-sync step-overhead A/B, chaos-harness outcome
        "ckpt": ckpt_snap,
        # auto-tuner v2 (ISSUE 10): one MeshSearch decision — plans
        # enumerated/pruned/trialed, winner predicted-vs-measured ms
        # (CPU-relative off-TPU), search wall seconds, cache hits
        "tune": tune_snap,
        # plan observatory (ISSUE 13): measured per-op attribution of
        # one profiled window (coverage vs device step wall, residual
        # explicit, category shares, dense/sparse split) + per-term
        # cost-model calibration ratios (CPU-relative off-TPU)
        "profile": profile_snap,
        # numerics observatory (ISSUE 17): per-layer stats attribution
        # on the sampled rig, drift-sentinel clean/perturbed self-test
        # (CPU-relative interpret-mode agreement), host consume cost
        "numerics": numerics_snap,
        # ops observatory (ISSUE 20): run-lifetime goodput fraction +
        # badput breakdown from the chaos rig, journal-emit /
        # alert-eval unit costs (CPU-relative)
        "ops": ops_snap,
        # same-round A/B under the previous round's harness params,
        # recorded iff bench_version bumped this round (VERDICT r5
        # item 6); tools/check_regression.py requires it to treat a
        # version-bump delta as explained
        "ab_vs_prev_harness": ab_snap,
        # harness provenance (VERDICT r5 item 6): exactly what this
        # number was measured with, so cross-round deltas are
        # attributable when the bench harness itself changes — compare
        # values only between rounds whose harness blocks match
        "harness": {
            "bench_sha256": _harness_hash(),
            "steps_measured": steps,
            "warmup_steps": warmup,
            "batch_size": bs,
            "seq_len": T,
            "vocab_size": cfg.vocab_size,
            "n_feed_batches": 4,
            "baseline_batch_size": small_bs,
            "baseline_steps": cmp_steps,
        },
    }
    if wire.get("dense_allreduce_bytes"):
        # north-star secondary metric: sparse-grad bytes on wire per step
        # vs shipping dense [V, D] gradients
        result["sparse_grad_bytes_on_wire"] = wire["sparse_path_bytes"]
        result["dense_grad_bytes_equivalent"] = \
            wire["dense_allreduce_bytes"]
    if on_cpu:
        # The CPU smoke config is still orders of magnitude below the
        # flagship's 793k vocab, so always attach the FLAGSHIP
        # wire-bytes accounting too; it's trace-time-exact and costs one
        # abstract eval.
        try:
            sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            from tools.wire_bytes_report import flagship_accounting
            flag = flagship_accounting(n_chips)
            result["flagship_wire_bytes"] = {
                "sparse_path_bytes": flag["sparse_path_bytes"],
                "dense_allreduce_bytes": flag["dense_allreduce_bytes"],
                "sparse_over_dense": flag["sparse_over_dense"],
            }
            # the tuned configuration (bf16 row planes + per-table
            # overflow-free dedup capacities): 0.65% of the reference's
            # fp32 dense all-reduce — perf/WIRE_BYTES_r04.json has the
            # full accounting
            opt = flagship_accounting(n_chips, table_dtype="bfloat16",
                                      dedup_capacity="auto")
            result["flagship_wire_bytes_optimized"] = {
                "table_dtype": "bfloat16",
                "dedup_capacity": opt["config"]["dedup_capacity"],
                "overflow_free":
                    opt["config"]["dedup_capacity_overflow_free"],
                "sparse_path_bytes": opt["sparse_path_bytes"],
                "dense_fp32_reference_bytes":
                    opt["dense_fp32_reference_bytes"],
                "sparse_over_dense_fp32_ref":
                    opt["sparse_over_dense_fp32_ref"],
            }
        except Exception as e:
            print(f"# flagship wire accounting failed: {e}", flush=True)
    print(json.dumps(result))


if __name__ == "__main__":
    if os.environ.get("PARALLAX_BENCH_WORKER"):
        worker_main()
    else:
        main()
