"""Benchmark driver: prints ONE JSON line with the headline metric.

Runs the flagship hybrid model (sharded embedding + dense layers) on the
available hardware and reports training throughput in examples/sec/chip.
``vs_baseline`` compares the HYBRID engine against the pure dense-AR path
(everything replicated, dense gradients) on the same hardware — the same
comparison the reference's README charts make against stock
TensorFlow/Horovod (reference README.md:27-41).
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


def _bench_once(run_option: str, vocab: int, dim: int, hidden: int,
                batch: int, steps: int = 30, warmup: int = 5) -> float:
    import parallax_tpu as parallax

    import __graft_entry__ as ge
    model = ge._flagship_model(vocab, dim, hidden)
    cfg = parallax.Config(run_option=run_option, search_partitions=False)
    sess, *_ = parallax.parallel_run(model, parallax_config=cfg)
    rng = np.random.default_rng(0)

    def make_batch():
        return {
            "ids": rng.integers(0, vocab, (batch,)).astype(np.int32),
            "labels": rng.integers(0, vocab, (batch,)).astype(np.int32),
        }

    batches = [make_batch() for _ in range(8)]
    for i in range(warmup):
        sess.run("loss", feed_dict=batches[i % 8])
    jax.block_until_ready(sess.state.params)
    t0 = time.perf_counter()
    for i in range(steps):
        sess.run("loss", feed_dict=batches[i % 8])
    jax.block_until_ready(sess.state.params)
    dt = time.perf_counter() - t0
    sess.close()
    return batch * steps / dt


def main():
    n_chips = jax.device_count()
    vocab, dim, hidden, batch = 8192 * max(1, n_chips), 512, 1024, 4096

    hybrid = _bench_once("HYBRID", vocab, dim, hidden, batch)
    dense = _bench_once("AR", vocab, dim, hidden, batch)

    per_chip = hybrid / n_chips
    print(json.dumps({
        "metric": "hybrid_train_examples_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "examples/sec/chip",
        "vs_baseline": round(hybrid / dense, 4),
    }))


if __name__ == "__main__":
    main()
